"""sonata-mesh router frontend: one gRPC endpoint over N backend nodes.

The fleet tier (``serving/mesh.py``) made concrete: this server speaks
the exact sonata gRPC surface (same service path, same
:mod:`.grpc_messages` codec — existing clients point at the router
unchanged) and forwards every RPC to the backend sonata servers named by
``SONATA_MESH_BACKENDS`` / ``--backend``, with health-gated membership,
per-node breakers, least-outstanding routing, deadline propagation, and
drain/kill-safe rerouting supplied by
:class:`~sonata_tpu.serving.mesh.MeshRouter`.

Design points specific to the hop:

- **Streaming payloads are forwarded as raw bytes** — the router
  decodes the (tiny) request to learn the voice id but never touches
  the audio frames: backend chunks pass through byte-for-byte, which is
  most of why the router-hop TTFB overhead stays inside the MESH_r01
  budget.
- **The trace crosses the hop**: the router accepts (or generates) the
  ``x-request-id``, records its own span tree (admission →
  mesh-dispatch → stream-emit, with ``mesh-reroute`` spans on
  failover), and forwards the id to the backend — the backend's trace
  carries the same id, so one Perfetto load of both ``/debug/traces``
  shows router queue → node dispatch end to end.
- **Unary surface**: voice management (``LoadVoice`` / ``UnloadVoice``
  / ``SetSynthesisOptions``) records *desired state* in the placement
  plane (ISSUE 14) and applies it to the voice's assigned nodes
  (``SONATA_PLACEMENT_REPLICAS``, default all); the anti-entropy
  reconciler riding the membership probers replays missed ops to
  nodes that were down, breaker-open, or restarted later — one
  reachable node is enough for the RPC to succeed.  Lookups
  (``GetVoiceInfo`` / ``GetSynthesisOptions`` / ``ListVoices``) forward
  to a routable node (preferring converged holders of the requested
  voice); ``CheckHealth`` / ``GetSonataVersion`` answer for the router
  itself.
- **The router drains like a node**: SIGTERM runs the same pinned
  ``DRAIN_PHASES`` order (readiness off first, typed refusals, bounded
  in-flight wait) — the "voices" phase closes mesh membership probing
  instead of voices.
- **The router is the fleet's observability plane** (ISSUE 13): a
  :class:`~sonata_tpu.serving.fleetscope.FleetScope` rides the
  membership probers, scraping each node's ``/debug/scope/export`` and
  serving fleet-merged quantiles/SLO burn (`sonata_fleet_*` families),
  the ``/debug/fleet`` scoreboard, stitched cross-host traces at
  ``/debug/traces/stitched?id=``, and a fleet flight recorder that
  auto-dumps on node eviction, breaker trips, and fast-burn breaches.

Binds ``127.0.0.1:$SONATA_MESH_PORT`` (default 49315, one above the
backend default so a laptop runs both).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Iterator, Optional

import grpc

from .. import __version__
from ..core import OperationError, SonataError
from ..serving import (
    DeadlineExceeded,
    Draining,
    Overloaded,
    ServingRuntime,
    faults,
    synthcache,
    tracing,
)
from ..serving import fleetcache as fleetcache_mod
from ..serving import ledger as ledger_mod
from ..serving import tenancy as tenancy_mod
from ..serving.fleetscope import FleetScope
from ..serving.logs import configure_logging
from ..serving.mesh import (
    MeshRouter,
    _http_fetch,
    parse_backends,
    resolve_node_id,
)
from ..serving.placement import PlacementPlane, VoiceWarming
from ..serving.replicas import OPEN
from . import grpc_messages as pb
from .grpc_server import (
    _METHODS,
    _SERVICE_PATH,
    _add_trailers,
    _context_request_id,
    _ledger_record,
    _status_for,
    voice_id_for,
)

log = logging.getLogger("sonata.mesh")

DEFAULT_PORT = 49315
PORT_ENV = "SONATA_MESH_PORT"

#: router-side metric families, loop-registered like the scope's
#: GAUGE_FAMILIES so the sonata-lint metricsdoc pass resolves the names
MESH_COUNTER_FAMILIES = (
    ("sonata_mesh_routed_total", "routed",
     "Streaming requests routed into the mesh."),
    ("sonata_mesh_rerouted_total", "rerouted",
     "Requests rerouted to another node (route-class failure, "
     "draining refusal, or first-chunk hedge) before any audio "
     "streamed."),
    ("sonata_mesh_rerouted_draining_total", "rerouted_draining",
     "Of the reroutes, those caused by a typed draining refusal "
     "(rolling-deploy traffic, not faults)."),
    ("sonata_mesh_hedged_total", "hedged",
     "Of the reroutes, those fired by the first-chunk hedge budget "
     "(SONATA_MESH_HEDGE_MS)."),
    ("sonata_mesh_failed_total", "failed",
     "Requests that failed out of the mesh (typed to the client)."),
    ("sonata_mesh_breaker_opens_total", "breaker_opens",
     "Node circuit-breaker trips."),
    ("sonata_mesh_recovered_total", "recovered",
     "Node breakers closed again by a successful trial request."),
    ("sonata_mesh_probe_failures_total", "probe_failures",
     "Node health probes that failed (unreachable health plane)."),
)

MESH_NODE_GAUGES = (
    ("sonata_mesh_node_outstanding", "outstanding",
     "Router-side in-flight requests, per backend node."),
    ("sonata_mesh_node_breaker_state", "state",
     "Node breaker: 0 closed, 1 half-open, 2 open."),
    ("sonata_mesh_node_draining", "draining",
     "1 while the node reports draining (evicted from membership), "
     "else 0."),
    ("sonata_mesh_node_reported_outstanding", "reported_outstanding",
     "Backend-scraped occupancy (sonata_replica_outstanding sum, "
     "fallback sonata_in_flight), per node."),
)


def _classify_rpc_error(exc: BaseException) -> str:
    """gRPC-aware failure classes for the router's retry contract."""
    if isinstance(exc, Draining):
        return "draining"
    if isinstance(exc, faults.InjectedFault):
        return "route"
    code = getattr(exc, "code", None)
    code = code() if callable(code) else None
    if code == grpc.StatusCode.UNAVAILABLE:
        details = ""
        det = getattr(exc, "details", None)
        if callable(det):
            try:
                details = det() or ""
            except Exception:
                details = ""
        # a PR-9 draining refusal is a deploy (evict + immediate
        # reroute); every other UNAVAILABLE is a connect/route fault
        return "draining" if "draining" in details else "route"
    if code in (grpc.StatusCode.CANCELLED, grpc.StatusCode.INTERNAL):
        # CANCELLED: our own hedge/cleanup cancel (a client hangup
        # surfaces as GeneratorExit on the router generator, never as
        # this).  INTERNAL: how a SIGKILLed peer surfaces to streams
        # caught mid-handshake (RST_STREAM) — route_stream only ever
        # retries pre-first-chunk, so a genuine INTERNAL from a live
        # node still fails typed after the bounded retry.
        return "route"
    return "fatal"


class SonataMeshService:
    """RPC implementations over a :class:`MeshRouter` membership."""

    def __init__(self, router: MeshRouter,
                 runtime: Optional[ServingRuntime] = None):
        self.router = router
        self.runtime = runtime if runtime is not None else ServingRuntime()
        self.fleetcache = None  # built after the fleet scope (ISSUE 16)
        self._channels: dict = {}
        #: (addr, method) -> stream multicallable: building one per
        #: request costs real TTFB on the hop (measured by bench_mesh)
        self._stream_stubs: dict = {}
        self._chan_lock = threading.Lock()
        #: (metric, labels) pairs created by _register_metrics, so the
        #: teardown removes exactly what was registered (the per-voice
        #: series idiom from ServingRuntime.register_voice)
        self._node_series: list = []
        rt = self.runtime
        #: zero routable nodes must flip the router's /readyz — the
        #: fleet balancer routes around this router until a backend
        #: rejoins (probes flip it back with no restart)
        rt.health.add_readiness_gate(
            "mesh:nodes", lambda: self.router.routable_count() > 0)
        rt.health.set_ready(
            f"mesh router over {len(router.nodes)} node(s)")
        self._register_metrics()
        #: sonata-placement (ISSUE 14): the desired-state voice
        #: registry + anti-entropy reconciler.  Voice ops through this
        #: router are recorded and REPLAYED — a SIGKILLed-and-restarted
        #: backend rejoins and gets its voices back with no operator
        #: action; routing is voice-aware (converged holders only, a
        #: typed voice-warming refusal after the bounded wait).  The
        #: reconcile loop rides the router's per-node prober threads.
        self.placement = PlacementPlane(
            router,
            apply_load=self._apply_load,
            apply_unload=self._apply_unload,
            apply_options=self._apply_options)
        router.attach_placement(self.placement)
        self.placement.bind_metrics(rt.registry)
        #: sonata-fleetscope (ISSUE 13): fleet-merged quantiles/burn,
        #: the /debug/fleet scoreboard, stitched traces, and the fleet
        #: flight recorder — scraping rides the router's probers
        self.fleet = FleetScope(router, tracer=rt.tracer)
        router.attach_fleet(self.fleet)
        self.fleet.bind_metrics(rt.registry)
        rt.fleet = self.fleet  # the HTTP plane serves /debug/fleet
        self.fleet.start()
        #: sonata-fleetcache (ISSUE 16): cache-affinity routing, router
        #: single-flight, and hot-set replication.  Opt-in via
        #: SONATA_FLEETCACHE=1 — off, the router's routing decisions and
        #: stream path are byte-for-byte the PR-12 ones.
        if fleetcache_mod.resolve_enabled():
            self.fleetcache = fleetcache_mod.FleetCache(
                router, fleet=self.fleet)
            self.fleetcache.set_replicate_transport(self._replicate_stream)
            router.attach_fleetcache(self.fleetcache)
            self.fleetcache.bind_metrics(rt.registry)
        #: sonata-tenancy (ISSUE 17): when the router runs with a
        #: tenant table (SONATA_TENANTS — the runtime built rt.tenancy
        #: from it), quota enforcement moves HERE: routed streams are
        #: charged at the router and stamped with the
        #: x-sonata-tenant-quota marker so nodes skip double-charging
        #: (per-node buckets stay the fallback for direct traffic), and
        #: the table itself is pushed to every node's /debug/tenants on
        #: the prober threads — the placement desired-state pattern.
        self.tenancy_propagator = None
        if rt.tenancy is not None:
            self.tenancy_propagator = tenancy_mod.ConfigPropagator(
                rt.tenancy)
            router.attach_tenancy(self.tenancy_propagator)
        #: sonata-ledger (ISSUE 19): /debug/requests?id= on the router
        #: merges the serving node's own record into the hop record by
        #: x-request-id (the stitched-trace pattern) — one document
        #: shows router reroutes next to node-side cost
        if rt.ledger is not None:
            rt.ledger.set_node_record_fetcher(self._fetch_node_record)

    # -- placement replay transport (the plane's apply_* callables) ----------
    def _apply_load(self, node, config_path: str):
        info = self._call_unary(
            node, "LoadVoice", pb.VoicePath(config_path=config_path),
            pb.VoiceInfo, 600.0)  # a replayed load may compile cold
        self._learn_voice(info)
        return info

    def _apply_unload(self, node, voice_id: str) -> None:
        try:
            self._call_unary(node, "UnloadVoice",
                             pb.VoiceIdentifier(voice_id=voice_id),
                             pb.Empty, 60.0)
        except grpc.RpcError as e:
            code = getattr(e, "code", None)
            code = code() if callable(code) else None
            if code != grpc.StatusCode.NOT_FOUND:
                raise  # already gone there == retired

    def _apply_options(self, node, payload: bytes):
        req = pb.VoiceSynthesisOptions.decode(payload)
        resp = self._call_unary(node, "SetSynthesisOptions", req,
                                pb.SynthesisOptions, 30.0)
        # a replayed option change moves the node's cache key: keep the
        # router's per-voice key inputs in lock-step (ISSUE 16)
        if self.fleetcache is not None and resp is not None:
            self.fleetcache.update_options(req.voice_id, resp)
        return resp

    # -- fleet-cache plumbing (serving/fleetcache.py, ISSUE 16) --------------
    def _replicate_stream(self, node, rpc_name: str, payload: bytes,
                          key: str) -> None:
        """Replay a remembered synthesis request to ``node`` so its
        PR-15 cache warms the template (hot-set replication transport).
        The audio is drained and dropped — the side effect is the fill."""
        fn = self._stream_stub(node, rpc_name)
        md = (("x-request-id", f"replicate-{key[:12]}"),)
        for _ in fn(payload, timeout=60.0, metadata=md):
            pass

    def _learn_voice(self, info) -> None:
        """Teach the affinity tier a voice's cache-key inputs from a
        node's VoiceInfo response (scales, speaker map, audio shape)."""
        if self.fleetcache is not None and info is not None:
            self.fleetcache.learn_voice(info)

    def _register_metrics(self) -> None:
        r = self.runtime.registry
        router = self.router
        r.gauge(
            "sonata_mesh_nodes",
            "Backend nodes configured in the mesh."
        ).set_function(lambda: float(len(router.nodes)))
        r.gauge(
            "sonata_mesh_healthy_nodes",
            "Backend nodes currently routable (breaker not open, ready, "
            "not draining) — the router's readiness gate."
        ).set_function(lambda: float(router.routable_count()))
        for name, key, help_text in MESH_COUNTER_FAMILIES:
            r.counter(name, help_text).set_function(
                lambda k=key: float(router.stats.get(k, 0)))
        for name, attr, help_text in MESH_NODE_GAUGES:
            metric = r.gauge(name, help_text)
            for node in router.nodes:
                labels = {"node": node.spec.addr}
                metric.labels(**labels).set_function(
                    lambda n=node, a=attr: float(getattr(n, a)))
                self._node_series.append((metric, labels))

    def unregister_node_series(self) -> None:
        """Drop the per-node labeled series (teardown twin of
        :meth:`_register_metrics`), releasing the closures that would
        otherwise pin the router's nodes past shutdown."""
        for metric, labels in self._node_series:
            metric.remove(**labels)
        self._node_series = []

    # -- channels -------------------------------------------------------------
    def _channel(self, node) -> grpc.Channel:
        with self._chan_lock:
            ch = self._channels.get(node.spec.addr)
            if ch is None:
                # one cached channel per node; gRPC reconnects through
                # backend restarts, so membership rejoin needs no churn
                ch = grpc.insecure_channel(node.spec.addr)
                self._channels[node.spec.addr] = ch
            return ch

    def _stream_stub(self, node, name: str):
        key = (node.spec.addr, name)
        with self._chan_lock:
            stub = self._stream_stubs.get(key)
        if stub is None:
            channel = self._channel(node)
            stub = channel.unary_stream(
                f"/{_SERVICE_PATH}/{name}",
                request_serializer=None,
                response_deserializer=None)
            with self._chan_lock:
                self._stream_stubs[key] = stub
        return stub

    def _call_unary(self, node, name: str, request, resp_cls,
                    timeout_s: float):
        fn = self._channel(node).unary_unary(
            f"/{_SERVICE_PATH}/{name}",
            request_serializer=lambda m: m.encode(),
            response_deserializer=resp_cls.decode)
        return fn(request, timeout=timeout_s)

    def _routable_node(self, context, voice_id: Optional[str] = None):
        nodes = [n for n in self.router.nodes
                 if n.state != OPEN and n.ready and not n.draining]
        if voice_id and self.placement.has_voice(voice_id):
            # voice-aware lookup forwarding: prefer a converged holder
            # so GetVoiceInfo does not 404 off a not-yet-reconciled node
            holders = [n for n in nodes
                       if n.loaded_voices is None
                       or voice_id in n.loaded_voices]
            if holders:
                nodes = holders
        if not nodes:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"mesh {self.router.name!r}: no routable "
                          "backend node")
        return nodes[0]

    # -- unary RPCs -----------------------------------------------------------
    def GetSonataVersion(self, request: pb.Empty, context) -> pb.Version:
        return pb.Version(version=__version__)

    def CheckHealth(self, request: pb.Empty, context) -> pb.HealthStatus:
        h = self.runtime.health.snapshot()
        return pb.HealthStatus(live=h["live"], ready=h["ready"],
                               reason=h["reason"], version=__version__,
                               node_id=h.get("node_id") or "")

    def _fanout(self, name: str, request, resp_cls, context,
                timeout_s: float):
        """Voice management reaches every reachable node; the last
        response is returned (they agree — same voice config path ⇒
        same voice id on every node).  Any node failing fails the call
        typed: a half-loaded fleet is worse than a failed load."""
        self.runtime.drain.raise_if_draining()
        nodes = [n for n in self.router.nodes
                 if n.state != OPEN and not n.draining]
        if not nodes:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"mesh {self.router.name!r}: no reachable "
                          "backend node")
        last = None
        for node in nodes:
            try:
                last = self._call_unary(node, name, request, resp_cls,
                                        timeout_s)
            except grpc.RpcError as e:
                context.abort(
                    e.code() if callable(getattr(e, "code", None))
                    and e.code() is not None else grpc.StatusCode.UNKNOWN,
                    f"node {node.node_id}: {e.details() or ''}")
        return last

    def LoadVoice(self, request: pb.VoicePath, context) -> pb.VoiceInfo:
        """Record the voice as desired state, then load it onto its
        placement (``SONATA_PLACEMENT_REPLICAS`` nodes, default all).

        Unlike the PR-12 best-effort fan-out, one reachable node
        suffices for success — the anti-entropy reconciler replays the
        load to every other assigned node (including ones that are
        down, breaker-open, or restarted *later*), which is what closes
        the rejoins-without-voices gap.  Zero successes rolls the
        desired record back and fails typed."""
        if not request.config_path:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "config_path is required")
        self.runtime.drain.raise_if_draining()
        vid = voice_id_for(request.config_path)
        created = self.placement.record_load(vid, request.config_path)
        info, last_err = None, None
        for node in self.placement.assigned_nodes(vid):
            if node.state == OPEN or node.draining:
                continue  # the reconciler replays once it rejoins
            try:
                info = self._call_unary(node, "LoadVoice", request,
                                        pb.VoiceInfo, 600.0)
                self.placement.note_applied(node, vid)
                self.router.note_voice_loaded(node, vid)
            except grpc.RpcError as e:
                last_err = (node, e)
                log.warning("mesh %s: LoadVoice on node %s failed "
                            "(reconciler will replay): %s", self.router.name,
                            node.node_id, e)
        if info is None:
            if created:
                # the op reached nobody: no ghost desired state
                self.placement.forget_load(vid)
            if last_err is not None:
                node, e = last_err
                context.abort(
                    e.code() if callable(getattr(e, "code", None))
                    and e.code() is not None else grpc.StatusCode.UNKNOWN,
                    f"node {node.node_id}: {e.details() or ''}")
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"mesh {self.router.name!r}: no reachable "
                          "backend node to load the voice on")
        self._learn_voice(info)
        return info

    def UnloadVoice(self, request: pb.VoiceIdentifier,
                    context) -> pb.Empty:
        """Tombstone the voice (nothing ever resurrects it) and unload
        it from every reachable node; nodes that are down now are
        retired by the reconciler when they rejoin."""
        self.runtime.drain.raise_if_draining()
        vid = request.voice_id
        known = self.placement.record_unload(vid)
        found = False
        for node in self.router.nodes:
            if node.state == OPEN or node.draining:
                continue
            if (node.loaded_voices is not None
                    and vid not in node.loaded_voices and known):
                continue  # known-absent there: nothing to do
            try:
                self._call_unary(node, "UnloadVoice", request, pb.Empty,
                                 60.0)
                found = True
                self.router.note_voice_unloaded(node, vid)
            except grpc.RpcError as e:
                code = getattr(e, "code", None)
                code = code() if callable(code) else None
                if code == grpc.StatusCode.NOT_FOUND:
                    continue
                if not known:
                    context.abort(code or grpc.StatusCode.UNKNOWN,
                                  f"node {node.node_id}: "
                                  f"{e.details() or ''}")
                log.warning("mesh %s: UnloadVoice on node %s failed "
                            "(reconciler will retire): %s",
                            self.router.name, node.node_id, e)
        if not found and not known:
            # the unload found the voice NOWHERE and the registry never
            # knew it: roll the tombstone back out, or a node later
            # boot-loading this id would be silently retired by an op
            # the client was told failed
            self.placement.forget_unload(vid)
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no voice with id {vid}")
        if self.fleetcache is not None:
            self.fleetcache.forget_voice(vid)
        return pb.Empty()

    def SetSynthesisOptions(self, request: pb.VoiceSynthesisOptions,
                            context) -> pb.SynthesisOptions:
        """Apply the options to every current holder, then record the
        payload as desired state (replayed verbatim to late joiners by
        the reconciler).  Apply-before-record: an RPC that reaches no
        holder aborts typed with NOTHING recorded — the registry must
        never hold options the client was told failed.  Voices the
        registry has never seen — node boot-config voices — keep the
        PR-12 fan-out path."""
        vid = request.voice_id
        if not self.placement.has_voice(vid):
            last = self._fanout("SetSynthesisOptions", request,
                                pb.SynthesisOptions, context,
                                timeout_s=30.0)
            if self.fleetcache is not None and last is not None:
                self.fleetcache.update_options(vid, last)
            return last
        self.runtime.drain.raise_if_draining()
        last, last_err = None, None
        applied_nodes = []
        for node in self.placement.assigned_nodes(vid):
            if node.state == OPEN or node.draining:
                continue
            if (node.loaded_voices is not None
                    and vid not in node.loaded_voices):
                continue  # not resident yet: the load replay carries it
            try:
                last = self._call_unary(node, "SetSynthesisOptions",
                                        request, pb.SynthesisOptions,
                                        30.0)
                applied_nodes.append(node)
            except grpc.RpcError as e:
                last_err = (node, e)
        if last is None:
            if last_err is not None:
                node, e = last_err
                context.abort(
                    e.code() if callable(getattr(e, "code", None))
                    and e.code() is not None else grpc.StatusCode.UNKNOWN,
                    f"node {node.node_id}: {e.details() or ''}")
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"mesh {self.router.name!r}: no reachable "
                          f"holder of voice {vid}")
        self.placement.record_options(vid, request.encode())
        for node in applied_nodes:
            self.placement.note_applied(node, vid)
        if self.fleetcache is not None:
            self.fleetcache.update_options(vid, last)
        return last

    def _forward_one(self, name: str, request, resp_cls, context,
                     timeout_s: float = 15.0):
        node = self._routable_node(
            context, getattr(request, "voice_id", None))
        try:
            return self._call_unary(node, name, request, resp_cls,
                                    timeout_s)
        except grpc.RpcError as e:
            context.abort(
                e.code() if callable(getattr(e, "code", None))
                and e.code() is not None else grpc.StatusCode.UNKNOWN,
                f"node {node.node_id}: {e.details() or ''}")

    def GetVoiceInfo(self, request: pb.VoiceIdentifier,
                     context) -> pb.VoiceInfo:
        return self._forward_one("GetVoiceInfo", request, pb.VoiceInfo,
                                 context)

    def GetSynthesisOptions(self, request: pb.VoiceIdentifier,
                            context) -> pb.SynthesisOptions:
        return self._forward_one("GetSynthesisOptions", request,
                                 pb.SynthesisOptions, context)

    def ListVoices(self, request: pb.Empty, context) -> pb.VoiceList:
        return self._forward_one("ListVoices", request, pb.VoiceList,
                                 context)

    # -- streaming RPCs -------------------------------------------------------
    def SynthesizeUtterance(self, request: pb.Utterance,
                            context) -> Iterator[bytes]:
        return self._routed_stream("SynthesizeUtterance", request,
                                   context)

    def SynthesizeUtteranceRealtime(self, request: pb.Utterance,
                                    context) -> Iterator[bytes]:
        return self._routed_stream("SynthesizeUtteranceRealtime",
                                   request, context)

    def _fetch_node_record(self, request_id: str, node_id: str):
        """Fetch the serving node's own ledger record over its metrics
        plane (the fleet scope's scrape transport).  None on any miss —
        the router's hop record then stands alone.  Called at QUERY
        time only (one /debug/requests?id= lookup), never on the
        request path."""
        import json
        from urllib.parse import quote

        for node in self.router.nodes:
            if node.node_id != node_id:
                continue
            base = node.spec.metrics_base
            if base is None:
                return None
            status, body = _http_fetch(
                f"{base}/debug/requests?id={quote(request_id)}",
                timeout_s=2.0)
            if status != 200:
                return None
            try:
                records = json.loads(body).get("records") or []
            except (ValueError, AttributeError):
                return None
            return records[0] if records else None
        return None

    def _abort(self, context, rpc: str, code, detail: str,
               refusal: Optional[str] = None,
               error: Optional[str] = None) -> None:
        """Metrics + a typed ledger record + the ``x-request-id``
        trailer (refused requests are debuggable too), then abort
        (raises)."""
        self.runtime.failures.labels(rpc=rpc, code=code.name).inc()
        _add_trailers(context,
                      ("x-request-id", _context_request_id(context)))
        lg = self.runtime.ledger
        if lg is not None:
            rec = _ledger_record(self.runtime, context, f"mesh.{rpc}")
            ident = getattr(context, "_sonata_tenant", None)
            if ident is not None:
                rec.note(tenant=ident.name)
            if refusal is not None:
                lg.emit(rec, refusal=refusal)
            else:
                lg.emit(rec, outcome="error", error=error or code.name)
        context.abort(code, detail)

    def _routed_stream(self, name: str, request: pb.Utterance,
                       context) -> Iterator[bytes]:
        """Route one synthesis stream across the fleet, yielding the
        backend's chunks as raw bytes.  The admission slot, request
        trace, and deadline are the router's own; the per-node retry
        contract (reroute before first chunk, typed after) lives in
        :meth:`MeshRouter.route_stream`."""
        from contextlib import ExitStack

        rt = self.runtime
        rid = _context_request_id(context)
        rec = _ledger_record(self.runtime, context, f"mesh.{name}",
                             voice=request.voice_id or None)
        if rec is not None:
            rec.note(text_len=len(request.text or ""))
        t0 = time.monotonic()
        ttfb = None
        try:
            with rt.tracer.trace_request(
                    f"mesh.{name}", request_id=rid,
                    voice=request.voice_id or "") as trace:
                with ExitStack() as stack:
                    with tracing.span("admission"):
                        rt.drain.raise_if_draining()
                        stack.enter_context(rt.admission.admit())
                    rt.requests.labels(rpc=name).inc()
                    _add_trailers(context, ("x-request-id", rid))
                    deadline = rt.deadline_for(context)
                    payload = request.encode()
                    md = (("x-request-id", rid),)
                    # sonata-tenancy (ISSUE 17): classify here, charge
                    # AFTER the single-flight follow decision (a
                    # follower rides a cache fill — parity with the
                    # node's probe-before-charge order).  The forwarded
                    # metadata names the tenant and marks quota as
                    # router-enforced so the backend skips its bucket.
                    tn = rt.tenancy
                    identity = None
                    if tn is not None:
                        identity = tn.classify_context(context)
                        try:
                            # the ledger's refusal records read it back
                            context._sonata_tenant = identity
                        except Exception:
                            pass
                        md = md + (
                            (tenancy_mod.ROUTER_TENANT_HEADER,
                             identity.name),
                            (tenancy_mod.ROUTER_ENFORCED_HEADER,
                             tenancy_mod.ROUTER_ENFORCED_VALUE))
                    served = [None]

                    def start(node, timeout_s):
                        served[0] = node
                        # raw-bytes forward via a cached stub: no codec
                        # and no per-request stub build on the hot path
                        fn = self._stream_stub(node, name)
                        return fn(payload, timeout=timeout_s,
                                  metadata=md)

                    # fleet cache tier (ISSUE 16): derive the PR-15
                    # canonical key at the router.  ckey is None when
                    # the tier is off, the voice is unknown/uncacheable,
                    # or derivation failed — every None keeps the PR-12
                    # routing and stream path byte-for-byte.
                    fc = self.router.fleetcache
                    ckey = None
                    if fc is not None:
                        kind = ("realtime"
                                if name == "SynthesizeUtteranceRealtime"
                                else "utterance")
                        ckey = fc.routing_key(kind, request)
                    outcome, flight = "bypass", None
                    if fc is not None and ckey is not None:
                        # remember the encoded request so hot-set
                        # replication can replay it to a peer later
                        fc.note_payload(ckey, name, payload)
                        outcome, flight = fc.begin_stream(ckey)
                    if outcome == "follow":
                        # router single-flight follower: ride the
                        # leader's fill instead of re-synthesizing
                        n = 0
                        follow_bytes = 0
                        try:
                            with tracing.span("fleetcache-follow") as fsp:
                                first = True
                                for chunk, _aux in flight:
                                    n += 1
                                    follow_bytes += len(chunk)
                                    if first:
                                        first = False
                                        ttfb = time.monotonic() - t0
                                        rt.ttfb.observe(ttfb)
                                        fsp.annotate(
                                            ttfb_ms=round(ttfb * 1e3, 3))
                                    yield chunk
                                fsp.annotate(chunks=n)
                            rt.synth_latency.observe(
                                time.monotonic() - t0)
                            if rec is not None:
                                rec.note(
                                    tenant=(identity.name
                                            if identity is not None
                                            else None),
                                    cache="follow", chunks=n,
                                    bytes_out=follow_bytes, ttfb_s=ttfb)
                                rt.ledger.emit(rec)
                            return
                        except synthcache.LeaderFailed:
                            if n > 0:
                                # audio already streamed: the client
                                # stream is poisoned, fail typed (the
                                # never-resend-after-first-chunk rule)
                                raise
                            # leader died before our first chunk: fall
                            # through to an independent routed synth
                        finally:
                            flight.abandon()

                    if tn is not None:
                        # this stream synthesizes (bypass, fill, or a
                        # follower whose leader died pre-first-chunk):
                        # burn the tenant's router-side token now, and
                        # refuse typed with a machine-readable
                        # retry-after trailer when the bucket is dry
                        ok, retry_after = tn.charge(
                            identity._replace(router_enforced=False))
                        if not ok:
                            _add_trailers(
                                context,
                                (tenancy_mod.RETRY_AFTER_TRAILER,
                                 f"{retry_after:.3f}"))
                            self._abort(
                                context, name,
                                grpc.StatusCode.RESOURCE_EXHAUSTED,
                                f"tenant {identity.name!r} over quota; "
                                f"retry in {retry_after:.3f}s",
                                refusal="router-quota")
                        tn.note_admitted(identity.name)

                    fill = flight if outcome == "fill" else None
                    committed = False
                    try:
                        first = True
                        with tracing.span("stream-emit") as emit_sp:
                            n_chunks = 0
                            bytes_out = 0
                            for chunk in self.router.route_stream(
                                    start, deadline=deadline,
                                    request_id=rid,
                                    classify=_classify_rpc_error,
                                    voice=request.voice_id or None,
                                    affinity_key=ckey):
                                n_chunks += 1
                                bytes_out += len(chunk)
                                if first:
                                    first = False
                                    ttfb = time.monotonic() - t0
                                    rt.ttfb.observe(ttfb)
                                    emit_sp.annotate(
                                        ttfb_ms=round(ttfb * 1e3, 3))
                                if fill is not None:
                                    fill.add_chunk(chunk)
                                yield chunk
                            emit_sp.annotate(chunks=n_chunks)
                        if fill is not None:
                            fill.commit_fill()
                            committed = True
                        rt.synth_latency.observe(time.monotonic() - t0)
                    finally:
                        if fill is not None and not committed:
                            # error, deadline, or client hangup
                            # (GeneratorExit): wake followers so they
                            # fall back instead of waiting out the clock
                            fill.abort_fill()
                    if served[0] is not None:
                        # forward the serving node's identity to OUR
                        # client, like the backend does for us — a
                        # client of the router learns which process in
                        # the fleet actually synthesized its audio
                        _add_trailers(context, ("x-sonata-node-id",
                                                served[0].node_id))
                    if rec is not None:
                        # the hop's wide event: router-side cost plus
                        # which node synthesized and how many reroutes
                        # it took to get there — /debug/requests?id= on
                        # the router merges the node's own record in
                        cost = ledger_mod.cost_fields_from_trace(trace)
                        reroutes = cost.pop("reroutes", 0)
                        rec.note(
                            tenant=(identity.name
                                    if identity is not None else None),
                            chunks=n_chunks, bytes_out=bytes_out,
                            ttfb_s=ttfb,
                            router={"reroutes": reroutes,
                                    "node": (served[0].node_id
                                             if served[0] is not None
                                             else None)},
                            **cost)
                        rt.ledger.emit(rec)
        except VoiceWarming as e:
            # typed like a draining refusal (UNAVAILABLE, retryable):
            # the voice is desired but no holder has converged inside
            # the bounded placement wait — a reconcile is in flight
            self._abort(context, name, grpc.StatusCode.UNAVAILABLE,
                        str(e), refusal="voice-warming")
        except Overloaded as e:
            rt.shed.labels(source="mesh").inc()
            self._abort(context, name, _status_for(e), str(e),
                        refusal="overload")
        except DeadlineExceeded as e:
            rt.expired.inc()
            self._abort(context, name, _status_for(e), str(e),
                        refusal="deadline")
        except Draining as e:
            self._abort(context, name, _status_for(e), str(e),
                        refusal="draining")
        except grpc.RpcError as e:
            # backend failure after the retry budget (or after bytes
            # streamed): forward the backend's own status typed
            code = getattr(e, "code", None)
            code = code() if callable(code) else None
            det = getattr(e, "details", None)
            det = (det() if callable(det) else "") or ""
            self._abort(context, name,
                        code or grpc.StatusCode.UNKNOWN,
                        f"backend: {det}",
                        error=(code.name if code is not None
                               else "RpcError"))
        except SonataError as e:
            self._abort(context, name, _status_for(e), str(e),
                        error=type(e).__name__)
        except GeneratorExit:
            # client hangup mid-stream: "cancelled", not a server error
            if rec is not None:
                rt.ledger.emit(rec, outcome="cancelled")
            raise
        except BaseException as e:
            if rec is not None and not rec.emitted:
                rt.ledger.emit(rec, outcome="error",
                               error=type(e).__name__)
            raise

    # -- lifecycle ------------------------------------------------------------
    def drain(self, timeout_s: Optional[float] = None,
              reason: str = "shutdown") -> bool:
        """Graceful router drain, same pinned phase order as a node
        (``DRAIN_PHASES``): readiness off first, new streams refused
        typed, in-flight streams finish inside the budget, then the
        "voices" phase closes mesh membership probing and "runtime"
        tears the metrics plane down.  First caller wins."""
        rt = self.runtime
        if not rt.begin_drain(reason):
            return False
        d = rt.drain
        d.note_phase("readiness-off")
        d.note_phase("reject-admissions", in_flight=rt.admission.in_flight)
        t0 = time.monotonic()
        idle_ok = d.wait_idle(lambda: rt.admission.in_flight == 0,
                              timeout_s)
        d.note_phase("wait-in-flight", ok=idle_ok,
                     waited_ms=round((time.monotonic() - t0) * 1e3, 1),
                     stragglers=rt.admission.in_flight)
        self.router.close()
        if self.fleetcache is not None:
            self.fleetcache.close()  # wakes single-flight followers
        self.fleet.close()
        self.placement.close()
        self.unregister_node_series()
        d.note_phase("voices", closed=len(self.router.nodes))
        rt.close()
        d.note_phase("runtime")
        d.note_phase("done", stragglers=rt.admission.in_flight)
        return True

    def shutdown(self) -> None:
        """Immediate teardown (the abrupt sibling of :meth:`drain`):
        raced requests still refuse typed via the shared drain flag."""
        self.runtime.drain.begin("shutdown")
        self.runtime.health.set_not_ready("shutting down")
        self.router.close()
        if self.fleetcache is not None:
            self.fleetcache.close()
        self.fleet.close()
        self.placement.close()
        self.unregister_node_series()
        with self._chan_lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            try:
                ch.close()
            except Exception:
                pass
        self.runtime.close()


class _MeshHandler(grpc.GenericRpcHandler):
    """Same method table as the node server; the two streaming
    synthesis RPCs pass response bytes through unserialized."""

    def __init__(self, service: SonataMeshService):
        self._service = service

    def service(self, handler_call_details):
        path = handler_call_details.method
        prefix = f"/{_SERVICE_PATH}/"
        if not path.startswith(prefix):
            return None
        name = path[len(prefix):]
        entry = _METHODS.get(name)
        if entry is None:
            return None
        req_cls, resp_cls, streaming = entry
        method = getattr(self._service, name)
        if streaming:
            return grpc.unary_stream_rpc_method_handler(
                method, request_deserializer=req_cls.decode,
                response_serializer=None)  # raw backend bytes
        return grpc.unary_unary_rpc_method_handler(
            method, request_deserializer=req_cls.decode,
            response_serializer=lambda m: m.encode())


def create_mesh_server(port: Optional[int] = None, *,
                       backends=None,
                       host: str = "127.0.0.1",
                       max_workers: int = 32,
                       runtime: Optional[ServingRuntime] = None,
                       router: Optional[MeshRouter] = None,
                       max_in_flight: Optional[int] = None,
                       max_queue_depth: Optional[int] = None,
                       request_timeout_s: Optional[float] = None,
                       metrics_port: Optional[int] = None,
                       name: str = "mesh"
                       ) -> tuple:
    """Build (server, bound_port) for the router.  ``backends`` is a
    spec string, a list of specs, or None (``SONATA_MESH_BACKENDS``)."""
    from concurrent.futures import ThreadPoolExecutor

    port = port if port is not None else int(
        os.environ.get(PORT_ENV, DEFAULT_PORT))
    if router is None:
        if isinstance(backends, (list, tuple)):
            backends = ",".join(backends)
        specs = parse_backends(backends)
        router = MeshRouter(specs, name=name)
    if runtime is None:
        runtime = ServingRuntime(max_in_flight=max_in_flight,
                                 max_queue_depth=max_queue_depth,
                                 request_timeout_s=request_timeout_s)
    service = SonataMeshService(router, runtime=runtime)
    server = grpc.server(ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix="sonata_mesh"))
    server.add_generic_rpc_handlers((_MeshHandler(service),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        router.close()
        raise OperationError(f"cannot bind {host}:{port}")
    server.sonata_service = service
    server.sonata_runtime = runtime
    runtime.set_node_id(resolve_node_id(f"{host}:{bound}"))
    http_port = runtime.start_http(metrics_port)
    if http_port is not None:
        log.info("mesh metrics/health plane on http://127.0.0.1:%d",
                 http_port)
    return server, bound


def main(argv=None) -> int:
    configure_logging(env_level_var="SONATA_GRPC")
    import argparse

    ap = argparse.ArgumentParser(prog="sonata-mesh")
    ap.add_argument("--port", type=int, default=None,
                    help="router gRPC port (default $SONATA_MESH_PORT "
                         f"or {DEFAULT_PORT})")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--backend", action="append", default=[],
                    help="backend node spec host:grpc_port[/metrics_port]"
                         " (repeatable; default $SONATA_MESH_BACKENDS)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="router /metrics /healthz /readyz HTTP port "
                         "(0 = ephemeral; default $SONATA_METRICS_PORT "
                         "or disabled)")
    ap.add_argument("--request-timeout-s", type=float, default=None,
                    help="router-side default deadline when the client "
                         "set none (default $SONATA_REQUEST_TIMEOUT_S "
                         "or 120; <=0 disables)")
    ap.add_argument("--max-in-flight", type=int, default=None)
    ap.add_argument("--max-queue-depth", type=int, default=None)
    ap.add_argument("--log-level", default=None,
                    choices=("DEBUG", "INFO", "WARNING", "ERROR",
                             "CRITICAL"))
    ap.add_argument("--log-format", default=None,
                    choices=("text", "json"))
    args = ap.parse_args(argv)
    if args.log_level or args.log_format:
        configure_logging(args.log_level, args.log_format,
                          env_level_var="SONATA_GRPC")
    faults.warn_if_armed(log)

    server, port = create_mesh_server(
        args.port, host=args.host,
        backends=args.backend or None,
        metrics_port=args.metrics_port,
        max_in_flight=args.max_in_flight,
        max_queue_depth=args.max_queue_depth,
        request_timeout_s=args.request_timeout_s)
    server.start()
    service = server.sonata_service
    log.info("sonata-mesh v%s listening on %s:%d over %d backend "
             "node(s): %s", __version__, args.host, port,
             len(service.router.nodes),
             [n.spec.addr for n in service.router.nodes])
    # rolling restarts: the router drains like a node (readiness off
    # first, in-flight streams finish, typed refusals)
    from .grpc_server import install_signal_handlers

    install_signal_handlers(server)
    try:
        server.wait_for_termination()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop(grace=2.0)
        service.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
