"""Wire messages for the Sonata gRPC service.

Hand-written against the reference's proto contract
(``crates/frontends/grpc/proto/sonata_grpc.proto``) so existing Sonata gRPC
clients interoperate unchanged: same package (``sonata_grpc``), same service
and method names, same message field numbers and types.  Encoding rides
:mod:`sonata_tpu.utils.protowire` (no protoc plugin in this environment).

A copy of the contract as ``.proto`` source lives in
``proto/sonata_grpc.proto`` for client codegen.
"""

from __future__ import annotations

from ..utils.protowire import Field, Message

PACKAGE = "sonata_grpc"
SERVICE = "sonata_grpc"


# enums (proto: SynthesisMode, Quality)
class SynthesisMode:
    UNSPECIFIED = 0
    LAZY = 1
    PARALLEL = 2
    BATCHED = 3


class Quality:
    UNSPECIFIED = 0
    X_LOW = 1
    LOW = 2
    MEDIUM = 3
    HIGH = 4

    _FROM_STR = {"x_low": X_LOW, "low": LOW, "medium": MEDIUM, "high": HIGH}

    @classmethod
    def from_string(cls, s) -> int:
        return cls._FROM_STR.get((s or "").lower(), cls.UNSPECIFIED)


class Empty(Message):
    FIELDS = {}


class Version(Message):
    FIELDS = {"version": Field(1, "string")}


class VoiceIdentifier(Message):
    FIELDS = {"voice_id": Field(1, "string")}


class VoicePath(Message):
    FIELDS = {"config_path": Field(1, "string")}


class SynthesisOptions(Message):
    FIELDS = {
        "speaker": Field(1, "string"),
        "length_scale": Field(2, "float"),
        "noise_scale": Field(3, "float"),
        "noise_w": Field(4, "float"),
    }


class AudioInfo(Message):
    FIELDS = {
        "sample_rate": Field(1, "uint32"),
        "num_channels": Field(2, "uint32"),
        "sample_width": Field(3, "uint32"),
    }


class VoiceInfo(Message):
    FIELDS = {
        "voice_id": Field(1, "string"),
        "synth_options": Field(2, "message", SynthesisOptions),
        "speakers": Field(3, "map_int64_string"),
        "audio": Field(4, "message", AudioInfo),
        "language": Field(5, "string"),
        "quality": Field(6, "enum"),
        "supports_streaming_output": Field(7, "bool"),
    }


class SpeechArgs(Message):
    FIELDS = {
        "rate": Field(1, "uint32"),
        "volume": Field(2, "uint32"),
        "pitch": Field(3, "uint32"),
        "appended_silence_ms": Field(4, "uint32"),
    }


class Utterance(Message):
    FIELDS = {
        "voice_id": Field(1, "string"),
        "text": Field(2, "string"),
        "speech_args": Field(3, "message", SpeechArgs),
        "synthesis_mode": Field(4, "enum"),
        # sonata-tpu extensions: per-request realtime chunk scheduling
        # (0/absent ⇒ the reference's hardcoded 55/3)
        "realtime_chunk_size": Field(5, "uint32"),
        "realtime_chunk_padding": Field(6, "uint32"),
    }


class VoiceList(Message):
    """sonata-tpu extension: catalog of loaded voices."""

    FIELDS = {
        "voices": Field(1, "message", VoiceInfo, repeated=True),
    }


class VoiceSynthesisOptions(Message):
    FIELDS = {
        "voice_id": Field(1, "string"),
        "synthesis_options": Field(2, "message", SynthesisOptions),
    }


class SynthesisResult(Message):
    FIELDS = {
        "wav_samples": Field(1, "bytes"),
        "rtf": Field(2, "float"),
    }


class WaveSamples(Message):
    FIELDS = {
        "wav_samples": Field(1, "bytes"),
    }


class HealthStatus(Message):
    """sonata-tpu extension: liveness/readiness over the serving protocol
    (mirrors the HTTP /healthz + /readyz plane, ``serving/health.py``)."""

    FIELDS = {
        "live": Field(1, "bool"),
        "ready": Field(2, "bool"),
        "reason": Field(3, "string"),
        "version": Field(4, "string"),
        # stable node identity (SONATA_NODE_ID, default host:port) so a
        # fleet router health-checking over gRPC names the backend
        "node_id": Field(5, "string"),
    }
