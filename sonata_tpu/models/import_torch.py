"""Import Piper/VITS torch checkpoints into the native param pytree.

The reference never touches checkpoints — it consumes exported ONNX.  We
support the richer source too: Piper training checkpoints (`.ckpt`
pytorch-lightning) and plain state-dict `.pt/.pth` files, mapped name-by-name
from upstream VITS module naming (``enc_p.encoder.attn_layers.0.conv_q`` …)
onto our pytree, with torch→NTC layout transposition and weight-norm fusion.

``params_to_state_dict`` is the exact inverse — used both to export native
voices back to the torch naming convention and as the round-trip importer
test (no real checkpoint needed).
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import numpy as np

from ..core import FailedToLoadResource
from .config import VitsHyperParams

# lightning/piper wrap the generator under one of these prefixes
_PREFIXES = ("model_g.", "net_g.", "generator.", "model.", "")


def _t_conv(w: np.ndarray) -> np.ndarray:
    """torch Conv1d [C_out, C_in, K] → ours [K, C_in, C_out]."""
    return np.ascontiguousarray(w.transpose(2, 1, 0))


def _t_conv_back(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.transpose(2, 1, 0))


def _t_tconv(w: np.ndarray) -> np.ndarray:
    """torch ConvTranspose1d [C_in, C_out, K] → ours [K, C_in, C_out]."""
    return np.ascontiguousarray(w.transpose(2, 0, 1))


def _t_tconv_back(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.transpose(1, 2, 0))


def _fuse_weight_norm(sd: dict, prefix: str) -> np.ndarray:
    """Return the effective conv weight, fusing weight_g/weight_v if the
    checkpoint still carries weight norm (piper removes it for the decoder
    before ONNX export but training ckpts keep it)."""
    if f"{prefix}.weight" in sd:
        return np.asarray(sd[f"{prefix}.weight"])
    g = np.asarray(sd[f"{prefix}.weight_g"])
    v = np.asarray(sd[f"{prefix}.weight_v"])
    norm = np.sqrt(np.sum(v * v, axis=(1, 2), keepdims=True))
    return g * v / np.maximum(norm, 1e-12)


class _Reader:
    def __init__(self, sd: dict):
        self.sd = sd
        self.used: set[str] = set()

    def raw(self, name: str) -> np.ndarray:
        if name not in self.sd:
            raise FailedToLoadResource(f"checkpoint missing tensor: {name}")
        self.used.add(name)
        return np.asarray(self.sd[name], dtype=np.float32)

    def conv(self, prefix: str) -> dict:
        if f"{prefix}.weight" not in self.sd:
            for s in ("weight_g", "weight_v"):
                self.used.add(f"{prefix}.{s}")
        else:
            self.used.add(f"{prefix}.weight")
        w = _fuse_weight_norm(self.sd, prefix).astype(np.float32)
        return {"w": _t_conv(w), "b": self.raw(f"{prefix}.bias")}

    def tconv(self, prefix: str) -> dict:
        if f"{prefix}.weight" not in self.sd:
            for s in ("weight_g", "weight_v"):
                self.used.add(f"{prefix}.{s}")
        else:
            self.used.add(f"{prefix}.weight")
        w = _fuse_weight_norm(self.sd, prefix).astype(np.float32)
        return {"w": _t_tconv(w), "b": self.raw(f"{prefix}.bias")}

    def ln(self, prefix: str) -> dict:
        return {"gamma": self.raw(f"{prefix}.gamma").reshape(-1),
                "beta": self.raw(f"{prefix}.beta").reshape(-1)}


def state_dict_to_params(sd: dict, hp: VitsHyperParams, *, n_vocab: int,
                         n_speakers: int = 1) -> dict:
    """Map a (prefix-stripped) VITS generator state dict onto our pytree."""
    r = _Reader(sd)
    gin = n_speakers > 1

    # -- text encoder ------------------------------------------------------
    enc_layers = []
    for i in range(hp.n_layers):
        enc_layers.append({
            "attn": {
                "q": r.conv(f"enc_p.encoder.attn_layers.{i}.conv_q"),
                "k": r.conv(f"enc_p.encoder.attn_layers.{i}.conv_k"),
                "v": r.conv(f"enc_p.encoder.attn_layers.{i}.conv_v"),
                "o": r.conv(f"enc_p.encoder.attn_layers.{i}.conv_o"),
                "emb_rel_k": r.raw(f"enc_p.encoder.attn_layers.{i}.emb_rel_k"),
                "emb_rel_v": r.raw(f"enc_p.encoder.attn_layers.{i}.emb_rel_v"),
            },
            "ln1": r.ln(f"enc_p.encoder.norm_layers_1.{i}"),
            "ffn": {
                "c1": r.conv(f"enc_p.encoder.ffn_layers.{i}.conv_1"),
                "c2": r.conv(f"enc_p.encoder.ffn_layers.{i}.conv_2"),
            },
            "ln2": r.ln(f"enc_p.encoder.norm_layers_2.{i}"),
        })
    params: dict = {
        "enc_p": {
            "emb": r.raw("enc_p.emb.weight"),
            "encoder": {"layers": enc_layers},
            "proj": r.conv("enc_p.proj"),
        }
    }
    if params["enc_p"]["emb"].shape[0] != n_vocab:
        raise FailedToLoadResource(
            f"embedding table has {params['enc_p']['emb'].shape[0]} symbols, "
            f"config says {n_vocab}")

    # -- stochastic duration predictor -------------------------------------
    def dds(prefix: str, n: int) -> dict:
        layers = []
        for i in range(n):
            layers.append({
                "dw": {"w": _t_conv(_fuse_weight_norm(sd, f"{prefix}.convs_sep.{i}")
                                    .astype(np.float32)),
                       "b": r.raw(f"{prefix}.convs_sep.{i}.bias")},
                "pw": r.conv(f"{prefix}.convs_1x1.{i}"),
                "ln1": r.ln(f"{prefix}.norms_1.{i}"),
                "ln2": r.ln(f"{prefix}.norms_2.{i}"),
            })
            r.used.add(f"{prefix}.convs_sep.{i}.weight")
        return {"layers": layers}

    dp: dict = {
        "pre": r.conv("dp.pre"),
        "convs": dds("dp.convs", 3),
        "proj": r.conv("dp.proj"),
        "affine": {"m": r.raw("dp.flows.0.m").reshape(-1),
                   "logs": r.raw("dp.flows.0.logs").reshape(-1)},
        "flows": [],
    }
    for i in range(hp.dp_n_flows):
        t_idx = 2 * i + 1  # ConvFlow positions in torch ModuleList (Flips interleave)
        dp["flows"].append({
            "pre": r.conv(f"dp.flows.{t_idx}.pre"),
            "convs": dds(f"dp.flows.{t_idx}.convs", 3),
            "proj": r.conv(f"dp.flows.{t_idx}.proj"),
        })
    if gin and "dp.cond.weight" in sd:
        dp["cond"] = r.conv("dp.cond")
    params["dp"] = dp

    # -- residual coupling flow --------------------------------------------
    flow_layers = []
    for i in range(hp.flow_n_layers):
        t_idx = 2 * i  # Flip modules interleave at odd indices
        wn_prefix = f"flow.flows.{t_idx}.enc"
        wn = {
            "in": [r.conv(f"{wn_prefix}.in_layers.{j}")
                   for j in range(hp.flow_wn_layers)],
            "res_skip": [r.conv(f"{wn_prefix}.res_skip_layers.{j}")
                         for j in range(hp.flow_wn_layers)],
        }
        if gin and f"{wn_prefix}.cond_layer.bias" in sd:
            wn["cond"] = r.conv(f"{wn_prefix}.cond_layer")
        flow_layers.append({
            "pre": r.conv(f"flow.flows.{t_idx}.pre"),
            "wn": wn,
            "post": r.conv(f"flow.flows.{t_idx}.post"),
        })
    params["flow"] = {"layers": flow_layers}

    # -- HiFi-GAN decoder ---------------------------------------------------
    n_kernels = len(hp.resblock_kernel_sizes)
    dec: dict = {
        "conv_pre": r.conv("dec.conv_pre"),
        "ups": [r.tconv(f"dec.ups.{i}") for i in range(len(hp.upsample_rates))],
        "resblocks": [],
        "conv_post": r.conv("dec.conv_post"),
    }
    for i in range(len(hp.upsample_rates)):
        for j in range(n_kernels):
            k = i * n_kernels + j
            n_d = len(hp.resblock_dilation_sizes[j])
            dec["resblocks"].append({
                "convs1": [r.conv(f"dec.resblocks.{k}.convs1.{d}")
                           for d in range(n_d)],
                "convs2": [r.conv(f"dec.resblocks.{k}.convs2.{d}")
                           for d in range(n_d)],
            })
    if gin and "dec.cond.weight" in sd:
        dec["cond"] = r.conv("dec.cond")
    params["dec"] = dec

    if gin:
        params["emb_g"] = r.raw("emb_g.weight")

    # diagnostic: report generator tensors the mapping did not consume
    # (training-only heads like enc_q.* / dp.post_* are expected leftovers)
    leftovers = [k for k in sd if k not in r.used
                 and not k.startswith(("enc_q.", "dp.post"))]
    if leftovers:
        import logging

        logging.getLogger("sonata.import").debug(
            "unmapped checkpoint tensors: %s",
            ", ".join(sorted(leftovers)[:20]))

    return params


def params_to_state_dict(params: dict, hp: VitsHyperParams) -> dict:
    """Inverse of :func:`state_dict_to_params` (torch naming, torch layout)."""
    sd: dict[str, np.ndarray] = {}

    def put_conv(prefix, p):
        sd[f"{prefix}.weight"] = _t_conv_back(np.asarray(p["w"]))
        sd[f"{prefix}.bias"] = np.asarray(p["b"])

    def put_tconv(prefix, p):
        sd[f"{prefix}.weight"] = _t_tconv_back(np.asarray(p["w"]))
        sd[f"{prefix}.bias"] = np.asarray(p["b"])

    def put_ln(prefix, p):
        sd[f"{prefix}.gamma"] = np.asarray(p["gamma"])
        sd[f"{prefix}.beta"] = np.asarray(p["beta"])

    enc = params["enc_p"]
    sd["enc_p.emb.weight"] = np.asarray(enc["emb"])
    for i, layer in enumerate(enc["encoder"]["layers"]):
        for name in ("q", "k", "v", "o"):
            put_conv(f"enc_p.encoder.attn_layers.{i}.conv_{name}",
                     layer["attn"][name])
        sd[f"enc_p.encoder.attn_layers.{i}.emb_rel_k"] = np.asarray(
            layer["attn"]["emb_rel_k"])
        sd[f"enc_p.encoder.attn_layers.{i}.emb_rel_v"] = np.asarray(
            layer["attn"]["emb_rel_v"])
        put_ln(f"enc_p.encoder.norm_layers_1.{i}", layer["ln1"])
        put_conv(f"enc_p.encoder.ffn_layers.{i}.conv_1", layer["ffn"]["c1"])
        put_conv(f"enc_p.encoder.ffn_layers.{i}.conv_2", layer["ffn"]["c2"])
        put_ln(f"enc_p.encoder.norm_layers_2.{i}", layer["ln2"])
    put_conv("enc_p.proj", enc["proj"])

    dp = params["dp"]
    put_conv("dp.pre", dp["pre"])
    put_conv("dp.proj", dp["proj"])
    sd["dp.flows.0.m"] = np.asarray(dp["affine"]["m"]).reshape(-1, 1)
    sd["dp.flows.0.logs"] = np.asarray(dp["affine"]["logs"]).reshape(-1, 1)

    def put_dds(prefix, p):
        for i, layer in enumerate(p["layers"]):
            sd[f"{prefix}.convs_sep.{i}.weight"] = _t_conv_back(
                np.asarray(layer["dw"]["w"]))
            sd[f"{prefix}.convs_sep.{i}.bias"] = np.asarray(layer["dw"]["b"])
            put_conv(f"{prefix}.convs_1x1.{i}", layer["pw"])
            put_ln(f"{prefix}.norms_1.{i}", layer["ln1"])
            put_ln(f"{prefix}.norms_2.{i}", layer["ln2"])

    put_dds("dp.convs", dp["convs"])
    for i, flow in enumerate(dp["flows"]):
        t_idx = 2 * i + 1
        put_conv(f"dp.flows.{t_idx}.pre", flow["pre"])
        put_dds(f"dp.flows.{t_idx}.convs", flow["convs"])
        put_conv(f"dp.flows.{t_idx}.proj", flow["proj"])
    if "cond" in dp:
        put_conv("dp.cond", dp["cond"])

    for i, layer in enumerate(params["flow"]["layers"]):
        t_idx = 2 * i
        put_conv(f"flow.flows.{t_idx}.pre", layer["pre"])
        put_conv(f"flow.flows.{t_idx}.post", layer["post"])
        for j, c in enumerate(layer["wn"]["in"]):
            put_conv(f"flow.flows.{t_idx}.enc.in_layers.{j}", c)
        for j, c in enumerate(layer["wn"]["res_skip"]):
            put_conv(f"flow.flows.{t_idx}.enc.res_skip_layers.{j}", c)
        if "cond" in layer["wn"]:
            put_conv(f"flow.flows.{t_idx}.enc.cond_layer", layer["wn"]["cond"])

    dec = params["dec"]
    put_conv("dec.conv_pre", dec["conv_pre"])
    put_conv("dec.conv_post", dec["conv_post"])
    for i, up in enumerate(dec["ups"]):
        put_tconv(f"dec.ups.{i}", up)
    for k, block in enumerate(dec["resblocks"]):
        for d, c in enumerate(block["convs1"]):
            put_conv(f"dec.resblocks.{k}.convs1.{d}", c)
        for d, c in enumerate(block["convs2"]):
            put_conv(f"dec.resblocks.{k}.convs2.{d}", c)
    if "cond" in dec:
        put_conv("dec.cond", dec["cond"])

    if "emb_g" in params:
        sd["emb_g.weight"] = np.asarray(params["emb_g"])
    return sd


def strip_prefix(sd: dict) -> dict:
    """Unwrap lightning/piper module prefixes down to generator naming."""
    for prefix in _PREFIXES:
        if any(k.startswith(prefix + "enc_p.") for k in sd):
            n = len(prefix)
            return {k[n:]: v for k, v in sd.items() if k.startswith(prefix)}
    return sd


def import_torch_checkpoint(path: Union[str, Path], hp: VitsHyperParams, *,
                            n_vocab: int, n_speakers: int = 1) -> dict:
    try:
        import torch
    except ImportError as e:  # pragma: no cover
        raise FailedToLoadResource("torch not available for import") from e
    try:
        obj = torch.load(str(path), map_location="cpu", weights_only=True)
    except Exception:
        obj = torch.load(str(path), map_location="cpu", weights_only=False)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    sd = {k: v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)
          for k, v in obj.items()}
    return state_dict_to_params(strip_prefix(sd), hp, n_vocab=n_vocab,
                                n_speakers=n_speakers)
