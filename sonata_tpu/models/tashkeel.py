"""Tashkeel: Arabic diacritization as a JAX character tagger.

The reference delegates this to the ``libtashkeel`` Rust crate, which runs
its own bundled ONNX seq-tagging model whenever a voice's eSpeak language is
``ar`` (``crates/sonata/models/piper/src/lib.rs:63-77,270-281``).  Per the
survey's plan (SURVEY §2.2), the model itself moves on-device: a character
embedding → transformer encoder → per-character diacritic classifier,
reusing the same JAX blocks as the VITS text encoder, jitted with the same
text buckets.

The tagger predicts one of 16 diacritic combinations (haraka ± shadda,
tanwin forms, sukun, or none) to insert after each base character.

File format: ``.npz`` of the flat param pytree plus a ``__meta__`` JSON
blob (vocab + hyperparams), produced by :meth:`TashkeelModel.save`.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import FailedToLoadResource
from ..utils.buckets import bucket_for, pad_to
from . import modules as m
from .serialization import flatten_params, unflatten_params

# diacritic classes: index 0 = none; combinations a trained model can emit
DIACRITICS = [
    "",        # none
    "َ",  # fatha
    "ُ",  # damma
    "ِ",  # kasra
    "ْ",  # sukun
    "ً",  # fathatan
    "ٌ",  # dammatan
    "ٍ",  # kasratan
    "ّ",          # shadda
    "َّ",    # shadda + fatha
    "ُّ",    # shadda + damma
    "ِّ",    # shadda + kasra
    "ًّ",    # shadda + fathatan
    "ٌّ",    # shadda + dammatan
    "ٍّ",    # shadda + kasratan
    "ـ",  # tatweel (rare; kept for class-count parity)
]
_DIACRITIC_CHARS = set("".join(DIACRITICS))

_DEFAULT_VOCAB = list(
    " !\"#$%&'()*+,-./0123456789:;<=>?@[]^_`{|}~"
    "ءآأؤإئابةتثجحخدذرزسشصضطظعغفقكلمنهوىي"
    "،؛؟"
)


@dataclasses.dataclass(frozen=True)
class TashkeelHyperParams:
    hidden: int = 128
    filter: int = 512
    n_heads: int = 4
    n_layers: int = 3
    kernel: int = 3
    window: int = 16


def init_tashkeel(rng, hp: TashkeelHyperParams, n_vocab: int) -> dict:
    r_emb, r_enc, r_proj = jax.random.split(rng, 3)
    return {
        "emb": jax.random.normal(r_emb, (n_vocab, hp.hidden)) * 0.02,
        "encoder": m.init_transformer(
            r_enc, channels=hp.hidden, filter_channels=hp.filter,
            n_heads=hp.n_heads, n_layers=hp.n_layers, kernel=hp.kernel,
            window=hp.window),
        "proj": m._conv_init(r_proj, 1, hp.hidden, len(DIACRITICS)),
    }


def apply_tashkeel(params: dict, hp: TashkeelHyperParams, ids, lengths):
    """ids [B, T] → diacritic class logits [B, T, n_classes]."""
    from .vits import sequence_mask

    mask = sequence_mask(lengths, ids.shape[1])
    x = params["emb"][ids]
    x = m.transformer(x, mask, params["encoder"], n_heads=hp.n_heads,
                      window=hp.window)
    return m.conv1d(x, params["proj"]) * mask


def strip_diacritics(text: str) -> str:
    return "".join(ch for ch in text if ch not in _DIACRITIC_CHARS)


class TashkeelModel:
    """Inference wrapper with text bucketing and a jit cache."""

    def __init__(self, params: dict, hp: TashkeelHyperParams,
                 vocab: Optional[list[str]] = None):
        self.params = params
        self.hp = hp
        self.vocab = vocab or list(_DEFAULT_VOCAB)
        self._char_to_id = {c: i + 1 for i, c in enumerate(self.vocab)}  # 0=pad
        self._jit_cache: dict[int, object] = {}

    @property
    def n_vocab(self) -> int:
        return len(self.vocab) + 1

    @classmethod
    def random(cls, hp: Optional[TashkeelHyperParams] = None,
               seed: int = 0) -> "TashkeelModel":
        hp = hp or TashkeelHyperParams()
        vocab = list(_DEFAULT_VOCAB)
        params = init_tashkeel(jax.random.PRNGKey(seed), hp, len(vocab) + 1)
        return cls(params, hp, vocab)

    @classmethod
    def from_path(cls, path: Union[str, Path]) -> "TashkeelModel":
        try:
            import zipfile

            with np.load(Path(path), allow_pickle=False) as data:
                flat = {k: data[k] for k in data.files if k != "__meta__"}
                meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
            raise FailedToLoadResource(
                f"cannot load tashkeel model {path}: {e}") from e
        hp = TashkeelHyperParams(**meta.get("hyper", {}))
        return cls(unflatten_params(flat), hp, meta.get("vocab"))

    def save(self, path: Union[str, Path]) -> None:
        flat = flatten_params(self.params)
        meta = json.dumps({
            "hyper": dataclasses.asdict(self.hp),
            "vocab": self.vocab,
        }).encode("utf-8")
        np.savez(Path(path), __meta__=np.frombuffer(meta, dtype=np.uint8),
                 **flat)

    def _fn(self, t: int):
        fn = self._jit_cache.get(t)
        if fn is None:
            hp = self.hp

            def run(params, ids, lengths):
                return apply_tashkeel(params, hp, ids, lengths)

            fn = jax.jit(run)
            self._jit_cache[t] = fn
        return fn

    def diacritize(self, text: str) -> str:
        """Insert predicted diacritics after each Arabic character."""
        base = strip_diacritics(text)
        if not base:
            return text
        ids = [self._char_to_id.get(ch, 0) for ch in base]
        t = bucket_for(len(ids))
        ids_arr = jnp.asarray([pad_to(ids, t)], dtype=jnp.int32)
        lengths = jnp.asarray([len(ids)], dtype=jnp.int32)
        logits = self._fn(t)(self.params, ids_arr, lengths)
        classes = np.asarray(jnp.argmax(logits, axis=-1))[0, :len(ids)]
        out = []
        for ch, cls in zip(base, classes):
            out.append(ch)
            # only Arabic letters take diacritics
            if "ء" <= ch <= "ي":
                out.append(DIACRITICS[int(cls)])
        return "".join(out)
