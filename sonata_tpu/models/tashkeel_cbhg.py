"""CBHG tashkeel tagger: the architecture family of libtashkeel's bundled
ONNX model, natively in JAX.

The reference auto-creates a libtashkeel inference engine whenever a voice's
eSpeak language is ``ar`` (``crates/sonata/models/piper/src/lib.rs:63-77,
270-281,321-333``); libtashkeel_core (patched submodule, ``cargo.toml:18-19``)
runs its bundled ONNX sequence tagger — a CBHG-style model (character
embedding → conv bank → max-pool → conv projections → residual → highway
stack → bidirectional GRU → linear classifier) from the Arabic
diacritization literature.  That submodule is not checked out in this
environment, so this module reconstructs the architecture and validates the
weight import against genuine ``torch.onnx.export`` artifacts of a faithful
torch mirror (``tests/test_tashkeel_cbhg.py``) rather than the bundled file.

Import is *shape-driven*: bank size K, projection widths, highway depth,
GRU units, and the post-CBHG recurrent stack are all inferred from the
weights present, so config variants of the same family load without a
sidecar config.  BatchNorm (inference mode) is folded into the preceding
conv at import time — one less elementwise pass over HBM per layer.

TPU notes: convs run in NTC layout (MXU matmuls); the GRU/LSTM input
projections are hoisted out of ``lax.scan`` so the big matmuls batch over
time; the reverse direction reuses the forward scan on an index-gathered
flip of the valid region (static shapes, no ragged control flow).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import FailedToLoadResource
from ..utils.buckets import bucket_for, pad_to


# ---------------------------------------------------------------------------
# forward pass
# ---------------------------------------------------------------------------

def _conv_ntc(x, w, b, pad_left: int, pad_right: int):
    """Conv1d, ``x: [B, T, Cin]``, ``w: [K, Cin, Cout]`` → same-length out."""
    y = lax.conv_general_dilated(
        x, w, window_strides=(1,), padding=[(pad_left, pad_right)],
        dimension_numbers=("NHC", "HIO", "NHC"))
    return y + b


def _torch_same_pad(k: int) -> tuple[int, int]:
    """torch Conv1d(padding=k//2) then trim-to-T ≡ pad (k//2, (k-1)//2)."""
    return k // 2, (k - 1) // 2


def _gru_scan(x_proj, w_hh, b_hh, h0):
    """Scan a GRU over time.  ``x_proj: [B, T, 3H]`` already includes
    ``x @ W_ih^T + b_ih`` (hoisted out of the scan → one big MXU matmul).

    torch gate order (r, z, n); ``n`` uses linear-before-reset semantics:
    ``n = tanh(x_n + r * (h @ W_hn^T + b_hn))``.
    """
    H = w_hh.shape[1] // 3

    def cell(h, xp):
        hp = h @ w_hh + b_hh  # [B, 3H]
        r = jax.nn.sigmoid(xp[:, :H] + hp[:, :H])
        z = jax.nn.sigmoid(xp[:, H:2 * H] + hp[:, H:2 * H])
        n = jnp.tanh(xp[:, 2 * H:] + r * hp[:, 2 * H:])
        h = (1.0 - z) * n + z * h
        return h, h

    _, ys = lax.scan(cell, h0, jnp.swapaxes(x_proj, 0, 1))
    return jnp.swapaxes(ys, 0, 1)  # [B, T, H]


def _lstm_scan(x_proj, w_hh, b_hh, h0, c0):
    """torch LSTM gate order (i, f, g, o)."""
    H = w_hh.shape[1] // 4

    def cell(carry, xp):
        h, c = carry
        g = xp + h @ w_hh + b_hh
        i = jax.nn.sigmoid(g[:, :H])
        f = jax.nn.sigmoid(g[:, H:2 * H])
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:])
        c = f * c + i * gg
        h = o * jnp.tanh(c)
        return (h, c), h

    _, ys = lax.scan(cell, (h0, c0), jnp.swapaxes(x_proj, 0, 1))
    return jnp.swapaxes(ys, 0, 1)


def _flip_valid(x, lengths):
    """Reverse each row's first ``lengths[b]`` steps; tail is zeroed.

    Maps position ``t`` → ``L-1-t`` for ``t < L``.  Applying it twice
    restores the original order, so the same gather aligns the reverse
    scan's outputs back to forward positions.
    """
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)[None, :]
    L = lengths[:, None]
    idx = jnp.where(t < L, L - 1 - t, 0)
    flipped = jnp.take_along_axis(x, idx[:, :, None], axis=1)
    return flipped * (t < L)[:, :, None]


def _bidi(x, lengths, direction_params, scan_one):
    """Run fwd+bwd recurrences and concat features."""
    B = x.shape[0]
    outs = []
    for tag in ("fwd", "bwd"):
        p = direction_params[tag]
        xi = x if tag == "fwd" else _flip_valid(x, lengths)
        x_proj = xi @ p["w_ih"] + p["b_ih"]
        o = scan_one(x_proj, p, B)
        if tag == "bwd":
            o = _flip_valid(o, lengths)
        outs.append(o)
    return jnp.concatenate(outs, axis=-1)


def apply_cbhg(params: dict, ids, lengths):
    """``ids [B, T]`` int32 → diacritic logits ``[B, T, n_targets]``.

    Matches torch inference on the exact-length sequence: every conv input
    is masked to zero beyond ``lengths`` so boundary windows see the same
    zero padding torch sees at its true sequence end.
    """
    B, T = ids.shape
    t = jnp.arange(T)[None, :]
    mask = (t < lengths[:, None])[:, :, None].astype(jnp.float32)

    emb = params["embedding"][ids] * mask  # [B, T, E]
    x = emb
    if params.get("prenet"):
        for lin in params["prenet"]:
            x = jax.nn.relu(x @ lin["w"] + lin["b"]) * mask

    # conv bank: kernel sizes 1..K, BN pre-folded into w/b
    bank = []
    for i, c in enumerate(params["bank"]):
        pl, pr = _torch_same_pad(i + 1)
        bank.append(jax.nn.relu(_conv_ntc(x, c["w"], c["b"], pl, pr)))
    y = jnp.concatenate(bank, axis=-1) * mask

    # max-pool k=2 stride=1 (left pad = -inf ⇒ out[t] = max(y[t-1], y[t]))
    prev = jnp.pad(y[:, :-1], ((0, 0), (1, 0), (0, 0)),
                   constant_values=-jnp.inf)
    y = jnp.maximum(y, prev) * mask

    # conv projections (ReLU on all but the last), BN folded
    for i, c in enumerate(params["projs"]):
        pl, pr = _torch_same_pad(c["w"].shape[0])
        y = _conv_ntc(y, c["w"], c["b"], pl, pr)
        if i + 1 < len(params["projs"]):
            y = jax.nn.relu(y)
        y = y * mask

    if params.get("pre_highway") is not None:
        y = y @ params["pre_highway"]["w"]
    y = (y + x) * mask  # residual onto the (pre-)bank input

    for hw in params["highways"]:
        h = jax.nn.relu(y @ hw["H"]["w"] + hw["H"]["b"])
        tgate = jax.nn.sigmoid(y @ hw["T"]["w"] + hw["T"]["b"])
        y = (h * tgate + y * (1.0 - tgate)) * mask

    H = params["gru"]["fwd"]["w_hh"].shape[0]
    y = _bidi(y, lengths, params["gru"],
              lambda xp, p, b: _gru_scan(
                  xp, p["w_hh"], p["b_hh"], jnp.zeros((b, H)))) * mask

    for layer in params["post"]:
        Hl = layer["fwd"]["w_hh"].shape[0]
        y = _bidi(y, lengths, layer,
                  lambda xp, p, b: _lstm_scan(
                      xp, p["w_hh"], p["b_hh"], jnp.zeros((b, Hl)),
                      jnp.zeros((b, Hl)))) * mask

    logits = y @ params["out"]["w"] + params["out"]["b"]
    return logits * mask


# ---------------------------------------------------------------------------
# weight import (state-dict names → pytree), BN folding
# ---------------------------------------------------------------------------

_BN_EPS = 1e-5


def _fold_bn(w_oik: np.ndarray, bias: Optional[np.ndarray],
             gamma, beta, mean, var) -> tuple[np.ndarray, np.ndarray]:
    """Fold inference-mode BatchNorm into the preceding conv.

    ``w_oik`` is torch layout ``[Cout, Cin, K]``; returns NTC layout
    ``[K, Cin, Cout]`` plus a folded bias.
    """
    scale = gamma / np.sqrt(var + _BN_EPS)  # [Cout]
    w = w_oik * scale[:, None, None]
    b = (bias if bias is not None else 0.0) * scale + beta - mean * scale
    return np.transpose(w, (2, 1, 0)).astype(np.float32), b.astype(np.float32)


def _linear(sd, name) -> dict:
    w = sd[f"{name}.weight"]
    out = {"w": np.ascontiguousarray(w.T).astype(np.float32)}
    if f"{name}.bias" in sd:
        out["b"] = sd[f"{name}.bias"].astype(np.float32)
    else:
        out["b"] = np.zeros(w.shape[0], np.float32)
    return out


def _rnn_direction(sd, prefix: str, suffix: str) -> dict:
    try:
        w_ih = sd[f"{prefix}.weight_ih_l0{suffix}"].astype(np.float32)
        w_hh = sd[f"{prefix}.weight_hh_l0{suffix}"].astype(np.float32)
    except KeyError as e:
        raise FailedToLoadResource(
            f"tashkeel CBHG import: missing recurrent weights "
            f"{prefix}.*_l0{suffix} — unidirectional exports are not part "
            "of the CBHG family (its recurrences are bidirectional)") from e
    b_ih = sd.get(f"{prefix}.bias_ih_l0{suffix}")
    b_hh = sd.get(f"{prefix}.bias_hh_l0{suffix}")
    G = w_ih.shape[0]
    return {
        "w_ih": np.ascontiguousarray(w_ih.T),
        "w_hh": np.ascontiguousarray(w_hh.T),
        "b_ih": (b_ih if b_ih is not None else np.zeros(G)).astype(
            np.float32),
        "b_hh": (b_hh if b_hh is not None else np.zeros(G)).astype(
            np.float32),
    }


def _strip_wrappers(sd: dict) -> dict:
    """Drop common wrapper prefixes (``model.``, ``cbhg_model.``,
    ``module.``) when every key carries the same one."""
    for prefix in ("model.", "cbhg_model.", "module."):
        if sd and all(k.startswith(prefix) for k in sd):
            sd = {k[len(prefix):]: v for k, v in sd.items()}
    return sd


def state_dict_to_cbhg(sd: dict) -> dict:
    """Map a torch CBHG state dict (or ONNX initializers preserving those
    names) onto the :func:`apply_cbhg` pytree.  Hyperparameters are inferred
    from the keys/shapes present."""
    sd = _strip_wrappers({k: np.asarray(v) for k, v in sd.items()})
    if "embedding.weight" not in sd:
        raise FailedToLoadResource(
            "tashkeel CBHG import: no 'embedding.weight' initializer "
            f"(found {sorted(sd)[:8]}…)")
    params: dict = {"embedding": sd["embedding.weight"].astype(np.float32)}

    # optional prenet: prenet.layers.{i}.weight or prenet.fc{i}.weight
    prenet = []
    for i in range(8):
        for cand in (f"prenet.layers.{i}", f"prenet.fc{i + 1}"):
            if f"{cand}.weight" in sd:
                prenet.append(_linear(sd, cand))
                break
    params["prenet"] = prenet

    def conv_block(base: str) -> Optional[dict]:
        for conv_name in (f"{base}.conv1d", f"{base}.conv", base):
            if f"{conv_name}.weight" in sd:
                break
        else:
            return None
        w = sd[f"{conv_name}.weight"].astype(np.float32)
        bias = sd.get(f"{conv_name}.bias")
        for bn_name in (f"{base}.bn", f"{base}.batch_norm"):
            if f"{bn_name}.weight" in sd:
                wf, bf = _fold_bn(
                    w, bias, sd[f"{bn_name}.weight"].astype(np.float32),
                    sd[f"{bn_name}.bias"].astype(np.float32),
                    sd[f"{bn_name}.running_mean"].astype(np.float32),
                    sd[f"{bn_name}.running_var"].astype(np.float32))
                return {"w": wf, "b": bf}
        b = (bias if bias is not None else np.zeros(w.shape[0])).astype(
            np.float32)
        return {"w": np.transpose(w, (2, 1, 0)).copy(), "b": b}

    bank = []
    for i in range(64):
        blk = conv_block(f"cbhg.conv1d_banks.{i}")
        if blk is None:
            break
        bank.append(blk)
    if not bank:
        raise FailedToLoadResource(
            "tashkeel CBHG import: no conv bank (cbhg.conv1d_banks.*)")
    params["bank"] = bank

    projs = []
    for i in range(16):
        blk = conv_block(f"cbhg.conv1d_projections.{i}")
        if blk is None:
            break
        projs.append(blk)
    params["projs"] = projs

    if "cbhg.pre_highway.weight" in sd:
        w = sd["cbhg.pre_highway.weight"].astype(np.float32)
        params["pre_highway"] = {"w": np.ascontiguousarray(w.T)}
    else:
        params["pre_highway"] = None

    highways = []
    for i in range(16):
        if f"cbhg.highways.{i}.H.weight" not in sd:
            break
        highways.append({"H": _linear(sd, f"cbhg.highways.{i}.H"),
                         "T": _linear(sd, f"cbhg.highways.{i}.T")})
    params["highways"] = highways

    params["gru"] = {"fwd": _rnn_direction(sd, "cbhg.gru", ""),
                     "bwd": _rnn_direction(sd, "cbhg.gru", "_reverse")}

    # post-CBHG recurrent stack: any other '<name>.weight_ih_l0' keys,
    # in sorted order (covers post_cbhg.{i}./lstm./layers.{i}. variants)
    post = []
    seen = set()
    for key in sorted(sd):
        m = re.match(r"(.+)\.weight_ih_l0$", key)
        if not m or m.group(1) == "cbhg.gru" or m.group(1) in seen:
            continue
        seen.add(m.group(1))
        post.append({"fwd": _rnn_direction(sd, m.group(1), ""),
                     "bwd": _rnn_direction(sd, m.group(1), "_reverse")})
    params["post"] = post

    for out_name in ("projections", "fc", "out", "classifier"):
        if f"{out_name}.weight" in sd:
            params["out"] = _linear(sd, out_name)
            break
    else:
        raise FailedToLoadResource(
            "tashkeel CBHG import: no output projection "
            "(projections/fc/out/classifier)")
    return jax.tree_util.tree_map(jnp.asarray, params)


# ---------------------------------------------------------------------------
# ONNX import, including recurrent weights folded into GRU/LSTM nodes
# ---------------------------------------------------------------------------

def _rnn_keys_from_nodes(inits: dict, nodes: list) -> dict:
    """Recover torch-style recurrent weight entries from ONNX GRU/LSTM
    *nodes* when ``torch.onnx.export`` constant folding replaced the named
    parameter initializers with anonymous reordered constants.

    ONNX gate orders: GRU ``(z, r, h)`` vs torch ``(r, z, n)``; LSTM
    ``(i, o, f, c)`` vs torch ``(i, f, g, o)``.
    """
    out: dict = {}
    n_lstm = 0
    for node in nodes:
        op = node["op_type"]
        if op not in ("GRU", "LSTM"):
            continue
        ins = node["inputs"]
        if len(ins) < 3 or ins[1] not in inits or ins[2] not in inits:
            continue
        W, R = np.asarray(inits[ins[1]]), np.asarray(inits[ins[2]])
        B = (np.asarray(inits[ins[3]])
             if len(ins) > 3 and ins[3] in inits else None)
        n_gates = 3 if op == "GRU" else 4
        H = W.shape[1] // n_gates
        if op == "GRU":
            if node["attrs"].get("linear_before_reset", 0) == 0:
                raise FailedToLoadResource(
                    "tashkeel CBHG import: GRU node without "
                    "linear_before_reset — not a torch export; unsupported")
            reorder = np.r_[H:2 * H, 0:H, 2 * H:3 * H]  # (z,r,h) → (r,z,n)
            prefix = "cbhg.gru"
        else:
            # (i,o,f,c) → (i,f,g,o)
            reorder = np.r_[0:H, 2 * H:3 * H, 3 * H:4 * H, H:2 * H]
            prefix = f"post_rnn.{n_lstm}"
            n_lstm += 1
        dirs = [""]
        if node["attrs"].get("direction") == "bidirectional" or W.shape[0] == 2:
            dirs = ["", "_reverse"]
        for d, suffix in enumerate(dirs):
            out[f"{prefix}.weight_ih_l0{suffix}"] = W[d][reorder]
            out[f"{prefix}.weight_hh_l0{suffix}"] = R[d][reorder]
            if B is not None:
                nb = n_gates * H
                out[f"{prefix}.bias_ih_l0{suffix}"] = B[d][:nb][reorder]
                out[f"{prefix}.bias_hh_l0{suffix}"] = B[d][nb:][reorder]
    return out


def _folded_linears_from_nodes(inits: dict, nodes: list) -> dict:
    """Recover ``<base>.weight`` for Linear layers whose weights were
    constant-folded into anonymous ``onnx::MatMul_*`` tensors.

    The bias initializer keeps its name, so a ``MatMul(x, W) → Add(bias)``
    (or fused ``Gemm``) pair identifies the layer: the anonymous ``W`` is
    the torch weight pre-transposed to ``[in, out]``.
    """
    out: dict = {}
    produced_by = {o: n for n in nodes for o in n["outputs"]}
    for n in nodes:
        if n["op_type"] == "Gemm" and len(n["inputs"]) >= 3:
            w_name, b_name = n["inputs"][1], n["inputs"][2]
            if (b_name in inits and b_name.endswith(".bias")
                    and w_name in inits and not b_name.startswith("onnx::")):
                w = np.asarray(inits[w_name])
                if not n["attrs"].get("transB", 0):
                    w = w.T  # → torch [out, in]
                out[b_name[:-5] + ".weight"] = w
            continue
        if n["op_type"] != "Add" or len(n["inputs"]) != 2:
            continue
        bias_name = next(
            (i for i in n["inputs"]
             if i in inits and i.endswith(".bias")
             and not i.startswith("onnx::")), None)
        if bias_name is None:
            continue
        other = (n["inputs"][1] if n["inputs"][0] == bias_name
                 else n["inputs"][0])
        mm = produced_by.get(other)
        if mm is None or mm["op_type"] != "MatMul" or len(mm["inputs"]) != 2:
            continue
        w_name = mm["inputs"][1]
        if w_name in inits and w_name not in (bias_name,):
            w = np.asarray(inits[w_name])
            if w.ndim == 2:
                out[bias_name[:-5] + ".weight"] = np.ascontiguousarray(w.T)
    return out


def cbhg_from_onnx(path) -> dict:
    """Load CBHG params from an ONNX export (name-preserving or
    constant-folded)."""
    from .import_onnx import read_onnx_graph, resolve_identity_aliases

    inits, nodes = read_onnx_graph(path)
    inits = resolve_identity_aliases(inits, nodes)
    sd = {k: v for k, v in inits.items()}
    stripped = _strip_wrappers(dict(sd))
    if not any(k.endswith("gru.weight_ih_l0") for k in stripped):
        sd.update(_rnn_keys_from_nodes(inits, nodes))
    for name, w in _folded_linears_from_nodes(inits, nodes).items():
        sd.setdefault(name, w)
    # bias-less pre_highway can't be recovered via its bias; when the
    # projection width differs from the embedding width one is required —
    # match the unique anonymous [proj_out, emb] MatMul weight
    if "cbhg.pre_highway.weight" not in sd and "embedding.weight" in sd:
        emb_dim = int(np.asarray(sd["embedding.weight"]).shape[1])
        last_proj = None
        for i in range(16):
            key = f"cbhg.conv1d_projections.{i}.conv1d.weight"
            if key in sd:
                last_proj = int(np.asarray(sd[key]).shape[0])
        if last_proj is not None and last_proj != emb_dim:
            cands = {
                n["inputs"][1]
                for n in nodes
                if n["op_type"] == "MatMul" and len(n["inputs"]) == 2
                and n["inputs"][1] in inits
                and np.asarray(inits[n["inputs"][1]]).shape
                == (last_proj, emb_dim)}
            if len(cands) == 1:
                w = np.asarray(inits[cands.pop()])
                sd["cbhg.pre_highway.weight"] = np.ascontiguousarray(w.T)
    from .import_onnx import to_f32

    return state_dict_to_cbhg(to_f32(sd))


# ---------------------------------------------------------------------------
# inference wrapper
# ---------------------------------------------------------------------------

class TashkeelCBHGModel:
    """Diacritization wrapper over :func:`apply_cbhg`.

    Character/target id maps default to the package's Arabic vocab and
    diacritic class list; a real artifact's own maps load from a JSON
    sidecar ``<model>.json`` with ``input_id_map`` (char → id) and
    ``target_id_map`` (diacritic string → id) — the same maps libtashkeel
    keeps as JSON resources beside its model.  Long inputs are chunked at
    ``max_len`` on whitespace (libtashkeel caps input length the same way).
    """

    def __init__(self, params: dict, *,
                 input_id_map: Optional[dict] = None,
                 target_id_map: Optional[dict] = None,
                 max_len: int = 315):
        from .tashkeel import DIACRITICS, _DEFAULT_VOCAB

        self.params = params
        self._char_to_id = (dict(input_id_map) if input_id_map else
                            {c: i + 1 for i, c in enumerate(_DEFAULT_VOCAB)})
        tmap = (dict(target_id_map) if target_id_map else
                {d: i for i, d in enumerate(DIACRITICS)})
        n_targets = int(np.asarray(params["out"]["b"]).shape[0])
        self._id_to_target = [""] * n_targets
        for diac, i in tmap.items():
            if 0 <= int(i) < n_targets:
                self._id_to_target[int(i)] = diac
        self.max_len = max_len
        self._apply = jax.jit(apply_cbhg)

    @classmethod
    def from_path(cls, path) -> "TashkeelCBHGModel":
        path = Path(path)
        params = cbhg_from_onnx(path)
        meta = {}
        sidecar = path.with_suffix(".json")
        if sidecar.exists():
            try:
                meta = json.loads(sidecar.read_text(encoding="utf-8"))
            except (OSError, ValueError) as e:
                raise FailedToLoadResource(
                    f"bad tashkeel sidecar {sidecar}: {e}") from e
        return cls(params,
                   input_id_map=meta.get("input_id_map"),
                   target_id_map=meta.get("target_id_map"),
                   max_len=int(meta.get("max_len", 315)))

    def _tag_chunk(self, base: str) -> str:
        ids = [self._char_to_id.get(ch, 0) for ch in base]
        t = bucket_for(len(ids))  # jit re-traces per bucket width only
        ids_arr = jnp.asarray([pad_to(ids, t)], dtype=jnp.int32)
        lengths = jnp.asarray([len(ids)], dtype=jnp.int32)
        logits = self._apply(self.params, ids_arr, lengths)
        classes = np.asarray(jnp.argmax(logits, axis=-1))[0, :len(ids)]
        out = []
        for ch, cls in zip(base, classes):
            out.append(ch)
            if "ء" <= ch <= "ي":  # only Arabic letters take diacritics
                out.append(self._id_to_target[int(cls)])
        return "".join(out)

    def diacritize(self, text: str) -> str:
        from .tashkeel import strip_diacritics

        base = strip_diacritics(text)
        if not base.strip():
            return text
        if len(base) <= self.max_len:
            return self._tag_chunk(base)
        # chunk on whitespace near max_len; hard-split a pathological
        # single token
        chunks, start = [], 0
        while start < len(base):
            end = min(start + self.max_len, len(base))
            if end < len(base):
                cut = base.rfind(" ", start, end)
                if cut > start:
                    end = cut + 1
            chunks.append(base[start:end])
            start = end
        return "".join(self._tag_chunk(c) if c.strip() else c
                       for c in chunks)
