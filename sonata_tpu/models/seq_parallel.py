"""Frame-domain sequence parallelism: coupling flow + HiFi-GAN across chips.

Long-context is first-class: the text encoder already rides the mesh's
``seq`` axis via ring attention, and this module extends the same axis
through the *frame* domain — the residual-coupling flow and the HiFi-GAN
decoder — so one long utterance's latent ``z`` (and its waveform) can
exceed a single chip's memory, sharded over frames.

Every frame-domain op has a bounded receptive field, so the schedule is
pure halo exchange (``parallel.ring.halo_exchange`` — neighbor ``ppermute``
over ICI, zeros at the true sequence ends, matching the zero padding an
unsharded conv sees):

- WaveNet convs (kernel 5, dilation 1): halo 2.
- HiFi-GAN resblock dilated convs (kernel ≤ 11, dilation ≤ 5): halo ≤ 25
  *samples at that stage's rate* per conv.
- Transposed upsampling convs (stride r, kernel k, pad (k−r)/2): extend
  the input by ``h = ceil((k−1−pad)/r)`` frames per side, run the same
  lhs-dilated conv, trim ``h·r`` output samples per side — exactly the
  global result, locally.

Numerics match the unsharded :func:`vits.flow_reverse` / :func:`vits.decode`
(tested in ``tests/test_parallel.py``).  The reference has no counterpart:
its decoder is a single-process ONNX session (``piper/src/lib.rs:342-399``).
"""

from __future__ import annotations

import math

from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..parallel.mesh import DATA_AXIS, SEQ_AXIS
from ..parallel.ring import halo_exchange
from . import modules as m
from .config import VitsHyperParams

Params = dict


def _conv_halo(x, p, *, dilation: int = 1):
    """SAME-padding conv over a sequence-sharded axis via halo exchange."""
    k = p["w"].shape[0]
    k_eff = (k - 1) * dilation + 1
    pl, pr = k_eff // 2, k_eff - 1 - k_eff // 2
    if pl == 0 and pr == 0:  # kernel-1: pointwise, no halo
        return m.conv1d(x, p)
    ext = halo_exchange(x, pl, pr)
    return m.conv1d(ext, p, dilation=dilation, padding=0)


def _tconv_halo(x, p, *, stride: int, padding: int):
    """Transposed conv over a sharded frame axis.

    Extends the input by ``h`` frames per side, applies the identical
    lhs-dilated conv, and trims ``h*stride`` output samples per side —
    the local segment of the global transposed conv.
    """
    k = p["w"].shape[0]
    a = k - 1 - padding
    h = max(math.ceil(a / stride), 0)
    ext = halo_exchange(x, h, h)
    y = m.conv_transpose1d(ext, p, stride=stride, padding=padding)
    trim = h * stride
    return y[:, trim: y.shape[1] - trim] if trim else y


def min_local_frames(hp: VitsHyperParams) -> int:
    """Smallest per-shard frame count for which every halo fits inside the
    immediate neighbor's shard at its stage's sample rate.

    ``halo_exchange`` is neighbor-only, so each stage needs
    ``local_len >= halo``; sample-rate halos (resblock dilated convs,
    transposed-conv extensions) divide back by the cumulative upsample
    product to frame units.
    """
    need = (7 - 1) // 2 + 1  # conv_pre/conv_post kernel 7 at frame rate
    need = max(need, (hp.flow_kernel_size - 1) // 2 + 1)  # WN convs
    prod = 1
    res_halo = max((k * d - d) // 2 + 1
                   for k, dils in zip(hp.resblock_kernel_sizes,
                                      hp.resblock_dilation_sizes)
                   for d in dils)
    for r, k in zip(hp.upsample_rates, hp.upsample_kernel_sizes):
        pad = (k - r) // 2
        h = max(math.ceil((k - 1 - pad) / r), 0) + 1
        need = max(need, math.ceil(h / prod))  # tconv input halo
        prod *= r
        need = max(need, math.ceil(res_halo / prod))
    return need


def _flow_reverse_local(pf: Params, hp: VitsHyperParams, z, mask, g):
    from . import vits

    return vits.flow_reverse(pf, hp, z, mask, g=g, conv=_conv_halo)


def _decode_local_impl(p: Params, hp: VitsHyperParams, z, g,
                       compute_dtype=None):
    from . import vits

    return vits.decode_with(p, hp, z, g=g, conv=_conv_halo,
                            tconv=_tconv_halo, compute_dtype=compute_dtype)


def flow_reverse_sp(pf: Params, hp: VitsHyperParams, z, mask, mesh, g=None):
    """Sequence-parallel :func:`vits.flow_reverse`: ``z`` [B, F, C] sharded
    over the mesh's seq axis along frames."""
    spec = P(DATA_AXIS, SEQ_AXIS, None)
    g_spec = P(DATA_AXIS, None, None)
    if g is None:
        fn = shard_map(
            lambda zz, mm, pp: _flow_reverse_local(pp, hp, zz, mm, None),
            mesh=mesh, in_specs=(spec, spec, P()), out_specs=spec)
        return fn(z, mask, pf)
    fn = shard_map(
        lambda zz, mm, gg, pp: _flow_reverse_local(pp, hp, zz, mm, gg),
        mesh=mesh, in_specs=(spec, spec, g_spec, P()), out_specs=spec)
    return fn(z, mask, g, pf)


def decode_sp(p: Params, hp: VitsHyperParams, z, mesh, g=None,
              compute_dtype=None):
    """Sequence-parallel :func:`vits.decode`: frames sharded over the seq
    axis; returns the waveform [B, F*hop] with samples sharded the same
    way.  ``compute_dtype`` follows the same reduced-precision policy as
    the unsharded path (halo exchanges ride the narrower dtype too)."""
    spec_z = P(DATA_AXIS, SEQ_AXIS, None)
    spec_out = P(DATA_AXIS, SEQ_AXIS)
    g_spec = P(DATA_AXIS, None, None)
    pd = {"dec": p["dec"]}  # decode only touches the generator subtree
    if g is None:
        fn = shard_map(
            lambda zz, pp: _decode_local_impl(pp, hp, zz, None,
                                              compute_dtype=compute_dtype),
            mesh=mesh, in_specs=(spec_z, P()), out_specs=spec_out)
        return fn(z, pd)
    fn = shard_map(
        lambda zz, gg, pp: _decode_local_impl(pp, hp, zz, gg,
                                              compute_dtype=compute_dtype),
        mesh=mesh, in_specs=(spec_z, g_spec, P()), out_specs=spec_out)
    return fn(z, g, pd)
