"""Piper voice configuration: JSON schema, synthesis params, phoneme-id
encoding, and VITS architecture hyper-parameters.

Parity targets (reference ``crates/sonata/models/piper/src/lib.rs``):

- ``ModelConfig`` fields mirror the Piper ``*.json`` sidecar the reference
  deserializes (``:144-158``): audio.sample_rate/quality, num_speakers,
  speaker_id_map, streaming flag, espeak.voice, inference scales,
  num_symbols, phoneme_id_map.
- ``SynthesisConfig`` mirrors ``PiperSynthesisConfig{speaker, noise_scale,
  length_scale, noise_w}`` (``:161-166``), seeded from the file (``:54-59``)
  and mutable at runtime behind a lock (``:215-231``).
- ``phonemes_to_ids`` reproduces the interleaved-pad encoding exactly
  (``:232-250``): ``[bos]``, then ``[id, pad]`` per IPA char, then
  ``[eos]``; unknown chars silently dropped (``:243``); BOS/EOS/PAD are the
  characters ``^ $ _`` resolved through the map (``:20-22,173-179``).

The architecture section has no reference counterpart — the reference runs a
black-box ONNX graph; we instantiate the graph natively, so the dims live in
:class:`VitsHyperParams` (quality presets match Piper's training configs).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from pathlib import Path
from typing import Optional, Union

from ..core import FailedToLoadResource

BOS_CHAR = "^"
EOS_CHAR = "$"
PAD_CHAR = "_"


@dataclasses.dataclass
class SynthesisConfig:
    """Runtime-tunable synthesis parameters (``piper/src/lib.rs:161-166``)."""

    speaker: Optional[tuple[str, int]] = None  # (name, sid)
    noise_scale: float = 0.667
    length_scale: float = 1.0
    noise_w: float = 0.8

    def copy(self) -> "SynthesisConfig":
        return dataclasses.replace(self)


@dataclasses.dataclass(frozen=True)
class VitsHyperParams:
    """VITS graph dimensions.  Defaults = Piper medium/high quality
    (22.05 kHz, hop 256)."""

    inter_channels: int = 192
    hidden_channels: int = 192
    filter_channels: int = 768
    n_heads: int = 2
    n_layers: int = 6
    kernel_size: int = 3
    attn_window: int = 4
    resblock_kernel_sizes: tuple[int, ...] = (3, 7, 11)
    resblock_dilation_sizes: tuple[tuple[int, ...], ...] = (
        (1, 3, 5), (1, 3, 5), (1, 3, 5),
    )
    upsample_rates: tuple[int, ...] = (8, 8, 2, 2)
    upsample_initial_channel: int = 512
    upsample_kernel_sizes: tuple[int, ...] = (16, 16, 4, 4)
    gin_channels: int = 512
    # stochastic duration predictor
    dp_filter_channels: int = 192
    dp_kernel_size: int = 3
    dp_n_flows: int = 4
    dp_num_bins: int = 10
    dp_tail_bound: float = 5.0
    # flow
    flow_n_layers: int = 4
    flow_wn_layers: int = 4
    flow_kernel_size: int = 5

    @property
    def hop_length(self) -> int:
        h = 1
        for r in self.upsample_rates:
            h *= r
        return h


# Piper quality presets.  "x_low" voices are 16 kHz with a slimmer decoder;
# low/medium/high share the 22.05 kHz geometry (quality differs by training).
QUALITY_PRESETS: dict[str, dict] = {
    "x_low": dict(
        hidden_channels=96, inter_channels=96, filter_channels=384,
        upsample_initial_channel=256,
    ),
    "low": {},
    "medium": {},
    "high": {},
}


@dataclasses.dataclass
class ModelConfig:
    """Parsed Piper voice config (``piper/src/lib.rs:144-158``)."""

    sample_rate: int
    quality: Optional[str]
    num_speakers: int
    speaker_id_map: dict[str, int]
    streaming: bool
    espeak_voice: str
    num_symbols: int
    phoneme_id_map: dict[str, list[int]]
    inference: SynthesisConfig
    hyper: VitsHyperParams
    language: Optional[str] = None
    path: Optional[Path] = None

    @classmethod
    def from_dict(cls, d: dict, path: Optional[Path] = None) -> "ModelConfig":
        audio = d.get("audio", {})
        espeak = d.get("espeak", {})
        inference = d.get("inference", {})
        quality = audio.get("quality")
        lang = d.get("language")
        if isinstance(lang, dict):
            lang = lang.get("code") or lang.get("family")
        preset = dict(QUALITY_PRESETS.get(quality or "", {}))
        preset.update(d.get("model", {}))  # our extension: explicit dims
        hyper = VitsHyperParams(**preset)
        sc = SynthesisConfig(
            noise_scale=float(inference.get("noise_scale", 0.667)),
            length_scale=float(inference.get("length_scale", 1.0)),
            noise_w=float(inference.get("noise_w", 0.8)),
        )
        return cls(
            sample_rate=int(audio.get("sample_rate", 22050)),
            quality=quality,
            num_speakers=int(d.get("num_speakers", 1)),
            speaker_id_map={str(k): int(v)
                            for k, v in (d.get("speaker_id_map") or {}).items()},
            streaming=bool(d.get("streaming", False)),
            espeak_voice=str(espeak.get("voice", "en-us")),
            num_symbols=int(d.get("num_symbols", 256)),
            phoneme_id_map={str(k): [int(i) for i in v]
                            for k, v in (d.get("phoneme_id_map") or {}).items()},
            inference=sc,
            hyper=hyper,
            language=lang,
            path=path,
        )

    @classmethod
    def from_path(cls, config_path: Union[str, Path]) -> "ModelConfig":
        p = Path(config_path)
        try:
            data = json.loads(p.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError) as e:
            raise FailedToLoadResource(f"cannot load voice config {p}: {e}") from e
        return cls.from_dict(data, path=p)

    # -- speaker helpers (reference core/src/lib.rs:95-113) -----------------
    def reversed_speaker_map(self) -> dict[int, str]:
        return {v: k for k, v in self.speaker_id_map.items()}

    # -- phoneme-id encoding (piper/src/lib.rs:232-250) ---------------------
    def phonemes_to_ids(self, phonemes: str) -> list[int]:
        id_map = self.phoneme_id_map
        pad = id_map.get(PAD_CHAR, [0])
        ids: list[int] = list(id_map.get(BOS_CHAR, [1]))
        for ch in phonemes:
            mapped = id_map.get(ch)
            if mapped is None:
                continue  # unknown chars silently dropped (:243)
            ids.extend(mapped)
            ids.extend(pad)  # interleaved pad after every phoneme
        ids.extend(id_map.get(EOS_CHAR, [2]))
        return ids


def default_phoneme_id_map() -> dict[str, list[int]]:
    """A self-contained IPA symbol table for voices created without a Piper
    JSON (tests, randomly-initialized voices).  Same structural conventions
    as Piper: ``_`` pad=0, ``^`` bos=1, ``$`` eos=2, then punctuation,
    space, and the IPA inventory."""
    symbols = ["_", "^", "$", " ", "!", "'", ",", "-", ".", ":", ";", "?"]
    ipa = (
        "abcdefhijklmnopqrstuvwxzæçðøħŋœǀǁǂǃɐɑɒɓɔɕɖɗɘəɚɛɜɞɟɠɡɢɣɤɥɦɧɨɪɫɬɭɮɯɰ"
        "ɱɲɳɴɵɶɸɹɺɻɽɾʀʁʂʃʄʈʉʊʋʌʍʎʏʐʑʒʔʕʘʙʛʜʝʟʡʢʰʲʷʼˈˌːˑ˞ˤ̩̪̯̺̻̃̊"
        "βθχᵻⱱ"
    )
    symbols.extend(dict.fromkeys(ipa))
    return {s: [i] for i, s in enumerate(symbols)}
