"""Piper voice configuration: JSON schema, synthesis params, phoneme-id
encoding, and VITS architecture hyper-parameters.

Parity targets (reference ``crates/sonata/models/piper/src/lib.rs``):

- ``ModelConfig`` fields mirror the Piper ``*.json`` sidecar the reference
  deserializes (``:144-158``): audio.sample_rate/quality, num_speakers,
  speaker_id_map, streaming flag, espeak.voice, inference scales,
  num_symbols, phoneme_id_map.
- ``SynthesisConfig`` mirrors ``PiperSynthesisConfig{speaker, noise_scale,
  length_scale, noise_w}`` (``:161-166``), seeded from the file (``:54-59``)
  and mutable at runtime behind a lock (``:215-231``).
- ``phonemes_to_ids`` reproduces the interleaved-pad encoding exactly
  (``:232-250``): ``[bos]``, then ``[id, pad]`` per IPA char, then
  ``[eos]``; unknown chars silently dropped (``:243``); BOS/EOS/PAD are the
  characters ``^ $ _`` resolved through the map (``:20-22,173-179``).

The architecture section has no reference counterpart — the reference runs a
black-box ONNX graph; we instantiate the graph natively, so the dims live in
:class:`VitsHyperParams` (quality presets match Piper's training configs).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from pathlib import Path
from typing import Optional, Union

from ..core import FailedToLoadResource

BOS_CHAR = "^"
EOS_CHAR = "$"
PAD_CHAR = "_"


@dataclasses.dataclass
class SynthesisConfig:
    """Runtime-tunable synthesis parameters (``piper/src/lib.rs:161-166``)."""

    speaker: Optional[tuple[str, int]] = None  # (name, sid)
    noise_scale: float = 0.667
    length_scale: float = 1.0
    noise_w: float = 0.8

    def copy(self) -> "SynthesisConfig":
        return dataclasses.replace(self)


@dataclasses.dataclass(frozen=True)
class VitsHyperParams:
    """VITS graph dimensions.  Defaults = Piper medium/high quality
    (22.05 kHz, hop 256)."""

    inter_channels: int = 192
    hidden_channels: int = 192
    filter_channels: int = 768
    n_heads: int = 2
    n_layers: int = 6
    kernel_size: int = 3
    attn_window: int = 4
    resblock_kernel_sizes: tuple[int, ...] = (3, 7, 11)
    resblock_dilation_sizes: tuple[tuple[int, ...], ...] = (
        (1, 3, 5), (1, 3, 5), (1, 3, 5),
    )
    upsample_rates: tuple[int, ...] = (8, 8, 2, 2)
    upsample_initial_channel: int = 512
    upsample_kernel_sizes: tuple[int, ...] = (16, 16, 4, 4)
    gin_channels: int = 512
    # stochastic duration predictor
    dp_filter_channels: int = 192
    dp_kernel_size: int = 3
    dp_n_flows: int = 4
    dp_num_bins: int = 10
    dp_tail_bound: float = 5.0
    # flow
    flow_n_layers: int = 4
    flow_wn_layers: int = 4
    flow_kernel_size: int = 5

    @property
    def hop_length(self) -> int:
        h = 1
        for r in self.upsample_rates:
            h *= r
        return h


# Piper quality presets.  "x_low" voices are 16 kHz with a slimmer decoder;
# low/medium/high share the 22.05 kHz geometry (quality differs by training).
QUALITY_PRESETS: dict[str, dict] = {
    "x_low": dict(
        hidden_channels=96, inter_channels=96, filter_channels=384,
        upsample_initial_channel=256,
    ),
    "low": {},
    "medium": {},
    "high": {},
}


@dataclasses.dataclass
class ModelConfig:
    """Parsed Piper voice config (``piper/src/lib.rs:144-158``)."""

    sample_rate: int
    quality: Optional[str]
    num_speakers: int
    speaker_id_map: dict[str, int]
    streaming: bool
    espeak_voice: str
    num_symbols: int
    phoneme_id_map: dict[str, list[int]]
    inference: SynthesisConfig
    hyper: VitsHyperParams
    language: Optional[str] = None
    path: Optional[Path] = None

    @classmethod
    def from_dict(cls, d: dict, path: Optional[Path] = None) -> "ModelConfig":
        audio = d.get("audio", {})
        espeak = d.get("espeak", {})
        inference = d.get("inference", {})
        quality = audio.get("quality")
        lang = d.get("language")
        if isinstance(lang, dict):
            lang = lang.get("code") or lang.get("family")
        preset = dict(QUALITY_PRESETS.get(quality or "", {}))
        preset.update(d.get("model", {}))  # our extension: explicit dims
        hyper = VitsHyperParams(**preset)
        sc = SynthesisConfig(
            noise_scale=float(inference.get("noise_scale", 0.667)),
            length_scale=float(inference.get("length_scale", 1.0)),
            noise_w=float(inference.get("noise_w", 0.8)),
        )
        return cls(
            sample_rate=int(audio.get("sample_rate", 22050)),
            quality=quality,
            num_speakers=int(d.get("num_speakers", 1)),
            speaker_id_map={str(k): int(v)
                            for k, v in (d.get("speaker_id_map") or {}).items()},
            streaming=bool(d.get("streaming", False)),
            espeak_voice=str(espeak.get("voice", "en-us")),
            num_symbols=int(d.get("num_symbols", 256)),
            phoneme_id_map={str(k): [int(i) for i in v]
                            for k, v in (d.get("phoneme_id_map") or {}).items()},
            inference=sc,
            hyper=hyper,
            language=lang,
            path=path,
        )

    @classmethod
    def from_path(cls, config_path: Union[str, Path]) -> "ModelConfig":
        p = Path(config_path)
        try:
            data = json.loads(p.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError) as e:
            raise FailedToLoadResource(f"cannot load voice config {p}: {e}") from e
        return cls.from_dict(data, path=p)

    # -- speaker helpers (reference core/src/lib.rs:95-113) -----------------
    def reversed_speaker_map(self) -> dict[int, str]:
        return {v: k for k, v in self.speaker_id_map.items()}

    # -- phoneme-id encoding (piper/src/lib.rs:232-250) ---------------------
    def phonemes_to_ids(self, phonemes: str) -> list[int]:
        ids, _dropped = self.phonemes_to_ids_diag(phonemes)
        return ids

    def phonemes_to_ids_diag(
            self, phonemes: str) -> tuple[list[int], list[str]]:
        """Encode, also returning the symbols the map could not encode.

        The reference drops unknown symbols silently (``:243``) — for a
        G2P-produced string that can delete load-bearing phonemes (e.g. a
        tone letter the voice's map lacks), so the drop list is surfaced
        here and aggregated by ``SpeechSynthesizer.phonemize_text``
        diagnostics; encoding behavior itself stays reference-identical.
        """
        id_map = self.phoneme_id_map
        pad = id_map.get(PAD_CHAR, [0])
        ids: list[int] = list(id_map.get(BOS_CHAR, [1]))
        dropped: list[str] = []
        for ch in phonemes:
            mapped = id_map.get(ch)
            if not mapped:
                # unknown symbol — or a present-but-EMPTY map entry in a
                # user-supplied config, which must degrade like unknown
                # rather than crash the encode path: dropped (:243)
                dropped.append(ch)
                continue
            # multi-id map entries contribute only their FIRST id — the
            # reference pushes ``id.first()`` per phoneme
            # (piper/src/lib.rs phonemes_to_input_ids), so extending with
            # the whole list would desynchronize sequences (and their
            # interleaved pads) from what the voice was trained on
            ids.append(mapped[0])
            ids.extend(pad)  # interleaved pad after every phoneme
        ids.extend(id_map.get(EOS_CHAR, [2]))
        return ids, dropped


def default_phoneme_id_map() -> dict[str, list[int]]:
    """The vendored piper-phonemize symbol table for voices created
    without a Piper JSON (tests, randomly-initialized voices).

    Ids 0-153 reproduce piper-phonemize's ``DEFAULT_PHONEME_ID_MAP``
    (``src/phoneme_ids.cpp``, a public ~154-entry constant) exactly, so
    phoneme-id sequences computed against this map are bit-identical to
    what a Piper voice trained with the default map expects.  Ids 154+
    are a documented extension block: IPA the hermetic G2P packs emit
    that the upstream table cannot encode (Chao tone letters carrying
    the entire zh/vi tone system, the glottalized-tone mark, secondary
    articulations, and combining diacritics).  A voice loaded from its
    own config JSON never sees this map.  Structural conventions:
    ``_`` pad=0, ``^`` bos=1, ``$`` eos=2.
    """
    upstream = (
        "_", "^", "$", " ", "!", "'", "(", ")", ",", "-", ".", ":",
        ";", "?",
        "a", "b", "c", "d", "e", "f", "h", "i", "j", "k", "l", "m",
        "n", "o", "p", "q", "r", "s", "t", "u", "v", "w", "x", "y",
        "z",
        "\u00e6", "\u00e7", "\u00f0", "\u00f8", "\u0127", "\u014b",
        "\u0153",
        "\u01c0", "\u01c1", "\u01c2", "\u01c3",
        "\u0250", "\u0251", "\u0252", "\u0253", "\u0254", "\u0255",
        "\u0256", "\u0257", "\u0258", "\u0259", "\u025a", "\u025b",
        "\u025c", "\u025e", "\u025f", "\u0260", "\u0261", "\u0262",
        "\u0263", "\u0264", "\u0265", "\u0266", "\u0267", "\u0268",
        "\u026a", "\u026b", "\u026c", "\u026d", "\u026e", "\u026f",
        "\u0270", "\u0271", "\u0272", "\u0273", "\u0274", "\u0275",
        "\u0276", "\u0278", "\u0279", "\u027a", "\u027b", "\u027d",
        "\u027e", "\u0280", "\u0281", "\u0282", "\u0283", "\u0284",
        "\u0288", "\u0289", "\u028a", "\u028b", "\u028c", "\u028d",
        "\u028e", "\u028f", "\u0290", "\u0291", "\u0292", "\u0294",
        "\u0295", "\u0298", "\u0299", "\u029b", "\u029c", "\u029d",
        "\u029f", "\u02a1", "\u02a2", "\u02b2",
        "\u02c8", "\u02cc", "\u02d0", "\u02d1", "\u02de",
        "\u03b2", "\u03b8", "\u03c7", "\u1d7b", "\u2c71",
        "0", "1", "2", "3", "4", "5", "6", "7", "8", "9",
        "\u0327", "\u0303", "\u032a", "\u032f", "\u0329",
        "\u02b0", "\u02e4", "\u03b5", "\u2193", "#", '"', "\u2191",
        "\u033a", "\u033b",
    )
    # extension block (ids 154+): hermetic-pack symbols upstream lacks
    extension = (
        "\u02e5", "\u02e6", "\u02e7", "\u02e8", "\u02e9",  # Chao tones
        "\u02c0",                                   # glottalized tone (vi)
        "\u02b7", "\u02bc",                        # labialized, ejective
        "\u02b1",                       # breathy-voice aspiration (ne/hi)
        "\u0325", "\u030a", "\u0306", "\u031d",  # voiceless/ring/breve/
        "\u0320", "\u0339", "\u031e", "\u0308",  # raised + retr/round/
        "\u032c",                                   # lowered/central/voiced
    )
    symbols = upstream + extension
    assert len(symbols) == len(set(symbols))
    return {s: [i] for i, s in enumerate(symbols)}
