"""Native parameter serialization: flat-keyed ``.npz`` archives.

The reference's "model state" is an immutable ONNX file next to the JSON
config (SURVEY §5 checkpoint/resume).  Our native equivalent is a numpy
``.npz`` holding the flattened param pytree — loadable with zero
dependencies, mmap-friendly, and the target format the ONNX/torch importers
convert into.  (Orbax is used for sharded multi-host checkpoints in
:mod:`sonata_tpu.parallel`; a single-voice file doesn't need it.)
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import jax
import numpy as np

SEP = "/"


def flatten_params(params) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = SEP.join(_segment(s) for s in path)
        flat[key] = np.asarray(leaf)
    return flat


def _segment(entry) -> str:
    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    return str(entry)


def unflatten_params(flat: dict[str, np.ndarray]):
    """Rebuild the nested dict/list pytree from flat keys."""
    root: dict = {}
    for key, value in flat.items():
        parts = key.split(SEP)
        node = root
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return _listify(root)


def _listify(node):
    """Convert dicts whose keys are 0..n-1 into lists (restores pytree
    structure of layer stacks)."""
    if not isinstance(node, dict):
        return node
    out = {k: _listify(v) for k, v in node.items()}
    keys = list(out.keys())
    if keys and all(k.isdigit() for k in keys):
        idx = sorted(int(k) for k in keys)
        if idx == list(range(len(idx))):
            return [out[str(i)] for i in idx]
    return out


def save_params(path: Union[str, Path], params) -> None:
    np.savez(Path(path), **flatten_params(params))


def load_params(path: Union[str, Path]):
    with np.load(Path(path)) as data:
        return unflatten_params({k: data[k] for k in data.files})
