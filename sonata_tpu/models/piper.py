"""PiperVoice: the concrete TTS model behind the ``Model`` protocol.

TPU-native analogue of the reference's ``sonata-piper`` crate
(``crates/sonata/models/piper/src/lib.rs``), replacing its two ORT sessions
with staged jitted XLA executables:

- reference ``VitsModel::infer_with_values`` (``:342-399``, one ONNX run)
  → two dispatches here: ``encode`` (text bucket) + ``synthesize`` (frame
  bucket).  The split exists because ONNX hides a data-dependent shape —
  the frame count — that XLA must see as static; bucketing bounds compiles.
- reference ``VitsStreamingModel`` (``:480-669``) → the same ``encode``
  plus ``acoustics``, then per-chunk jitted decodes following the
  ``AdaptiveMelChunker`` schedule (:mod:`.chunker`).
- reference ``speak_batch`` loops sentences through single inference
  (``:425-437``); here it is a true padded batch — one device program for
  the whole batch (the designed improvement, SURVEY §2.4).

Thread-safety: the synthesis config sits behind a lock (reference uses an
``RwLock``, ``:215-231``); jit caches are lock-protected; phonemization is
serialized inside the text backend.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from pathlib import Path
from typing import Any, Iterator, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..audio import Audio, AudioSamples
from ..core import (
    AudioInfo,
    BaseModel,
    FailedToLoadResource,
    OperationError,
    Phonemes,
)
from ..serving import tracing
from ..synth.batching import (
    BatchingCore,
    IterationLoop,
    WorkItem,
    drain_pending_futures,
    effective_batch_mode,
    resolve_batch_mode,
    try_set_exception,
    try_set_result,
)
from ..text import text_to_phonemes
from ..text.tashkeel import TashkeelEngine, get_default_engine
from ..utils.buckets import (
    BATCH_BUCKETS,
    FRAME_BUCKETS,
    TEXT_BUCKETS,
    bucket_for,
    pad_to,
)
from ..utils.dispatch_policy import (
    DispatchPolicy,
    resolve_policy,
    should_donate,
)
from . import decode_opts, vits
from .chunker import CROSSFADE_SAMPLES, plan_chunks
from .config import ModelConfig, SynthesisConfig, default_phoneme_id_map
from .serialization import load_params


class PiperVoice(BaseModel):
    """A loaded Piper voice: config + params + compiled-executable caches."""

    def __init__(self, config: ModelConfig, params, *, seed: int = 0,
                 tashkeel: Optional[TashkeelEngine] = None, mesh=None,
                 compute_dtype: Optional[str] = None,
                 dispatch_policy: "Optional[DispatchPolicy]" = None,
                 fused_epilogue: Optional[str] = None,
                 decode_quant: Optional[str] = None):
        self.config = config
        self.hp = config.hyper
        # int8 weight-only decoder arm (SONATA_DECODE_QUANT=int8):
        # per-channel symmetric quantization of the HiFi-GAN conv
        # weights at load, dequantized inside the jitted decode
        # (vits.decode_with) — activations stay f32/bf16.  Parity-gated
        # by the spectral-distance test in tests/test_decode_opts.py.
        self.decode_quant = decode_opts.resolve_decode_quant(decode_quant)
        if self.decode_quant == "int8":
            if mesh is not None:
                raise OperationError(
                    "SONATA_DECODE_QUANT=int8 does not compose with a "
                    "device mesh (param shardings assume f32 leaves)")
            if not decode_opts.decoder_is_quantized(params["dec"]):
                params = dict(params)
                params["dec"] = decode_opts.quantize_decoder(
                    params["dec"])
        # fused decode epilogue (SONATA_FUSED_EPILOGUE=pallas|lax|off,
        # default lax): streaming window decode + crossfade taper +
        # peak-scaled i16 quantize run as ONE device program per
        # (width, batch rung) — see _decode_windows_fused_fn.
        self.fused_epilogue = decode_opts.resolve_fused_epilogue(
            fused_epilogue)
        self.params = params
        self.mesh = mesh  # jax.sharding.Mesh → batch rides the data axis
        # Reduced-precision policy for the HiFi-GAN conv stack (the FLOPs):
        # "bfloat16" keeps the MXU in its native single-pass mode.  Audio
        # leaves the graph float32 either way (vits.decode_with casts back
        # before the final tanh); measured ~38 dB SNR vs float32 — below
        # the i16 output floor, so default stays float32 and serving can
        # opt in per deployment (SONATA_COMPUTE_DTYPE=bfloat16).
        import os

        compute_dtype = compute_dtype or os.environ.get(
            "SONATA_COMPUTE_DTYPE")
        if compute_dtype in (None, "", "float32", "f32"):
            self.compute_dtype = None
        elif compute_dtype in ("bfloat16", "bf16"):
            self.compute_dtype = jnp.bfloat16
        else:
            raise OperationError(
                f"unsupported compute_dtype {compute_dtype!r} "
                "(use float32 or bfloat16)")
        self.multi_speaker = config.num_speakers > 1
        self._synth_lock = threading.RLock()
        self._synth_config = config.inference.copy()
        self._jit_lock = threading.Lock()
        self._enc_cache: dict = {}
        self._full_cache: dict = {}
        self._aco_cache: dict = {}
        self._dec_cache: dict = {}
        self._stream_coalescer: "Optional[_StreamDecodeCoalescer]" = None
        self._stage_coalescer: "Optional[_StreamStageCoalescer]" = None
        #: iteration-mode engine (SONATA_BATCH_MODE=iteration): the
        #: persistent per-device decode loop; coexists with the
        #: dispatch-mode coalescer so the degradation ladder can force
        #: new streams back to dispatch mode while resident ones finish
        self._iter_decoder: "Optional[_IterationStreamDecoder]" = None
        #: voice id the serving runtime registered this model under —
        #: stamps the iteration loop's per-iteration scope attribution
        #: (the scheduler path carries it via trace_attrs instead)
        self.scope_voice: Optional[str] = None
        # backend-adaptive dispatch policy (utils/dispatch_policy): pass
        # one explicitly to pin the serving shape; None resolves lazily
        # on first use (env overrides → backend fast path → cached probe)
        # so plain construction never pays a probe dispatch.
        self._dispatch_policy = dispatch_policy
        self._policy_lock = threading.Lock()
        self._voice_closed = False
        # encodability diagnostics: symbols the voice's phoneme_id_map
        # could not encode (dropped, reference-identically, at encode
        # time — piper/src/lib.rs:243).  A nonzero rate means the G2P
        # front-end and the voice's symbol table disagree; for tonal
        # languages that can silently delete the whole tone system.
        self.drop_stats = {"symbols_total": 0, "symbols_dropped": 0,
                           "dropped": {}}
        self._warned_drops: set = set()
        # adaptive frame-budget estimator for the single-dispatch path:
        # running upper bound of frames per input id per unit length_scale.
        # Start optimistic — an underestimate costs one overflow retry on
        # the first batch, while an overestimate inflates every transfer
        # (the wav buffer scales with the frame bucket).
        self._frames_per_id = 2.5
        self._fpi_observed = False  # first real observation landed?
        self._fpi_lock = threading.Lock()
        self._rng_lock = threading.Lock()
        self._rng_counter = 0
        self._seed = seed
        # Arabic voices get the diacritizer automatically
        # (parity: piper/src/lib.rs:63-77)
        self._tashkeel = tashkeel
        if self._tashkeel is None and config.espeak_voice.startswith("ar"):
            self._tashkeel = get_default_engine()

    # ------------------------------------------------------------------
    # factories
    # ------------------------------------------------------------------

    @classmethod
    def from_config_path(cls, config_path: Union[str, Path],
                         **kwargs) -> "PiperVoice":
        """Load a voice from a Piper ``*.json`` config.

        Weight resolution (reference loads ``config path minus .json`` as
        ONNX, ``piper/src/lib.rs:98-108``): tries, in order, the sidecar
        ``<stem>.npz`` (native), ``<stem>.onnx`` (imported), ``<stem>.pt`` /
        ``.ckpt`` (torch checkpoint import).
        """
        config = ModelConfig.from_path(config_path)
        stem = Path(config_path)
        stem = stem.with_suffix("") if stem.suffix == ".json" else stem
        n_vocab = max(config.num_symbols,
                      1 + max((max(v) for v in config.phoneme_id_map.values()),
                              default=0))
        # Piper convention: "voice.onnx" + "voice.onnx.json", so the config
        # path minus ".json" may itself be the ONNX file (piper/lib.rs:98-108)
        onnx_path = stem if stem.suffix == ".onnx" else stem.with_suffix(".onnx")
        # streaming ("rt") voice directories split the exported graph into
        # encoder.onnx + decoder.onnx siblings of the config
        # (piper/src/lib.rs:90-96).  The two initializer sets partition the
        # same VITS weights; merged, they feed the one staged model — the
        # split *runtime* is superfluous here because the serving path is
        # already staged into encode/acoustics/decode executables.
        enc_path = Path(config_path).with_name("encoder.onnx")
        dec_path = Path(config_path).with_name("decoder.onnx")
        if stem.with_suffix(".npz").exists():  # native format stays first
            params = load_params(stem.with_suffix(".npz"))
        elif config.streaming and enc_path.exists() and dec_path.exists():
            try:
                from .import_onnx import import_onnx_weights
            except ImportError as e:
                raise FailedToLoadResource(
                    f"ONNX weight import unavailable: {e}") from e
            params = import_onnx_weights(
                (enc_path, dec_path), config.hyper, n_vocab=n_vocab,
                n_speakers=config.num_speakers)
        elif onnx_path.exists():
            try:
                from .import_onnx import import_onnx_weights
            except ImportError as e:
                raise FailedToLoadResource(
                    f"ONNX weight import unavailable: {e}") from e
            params = import_onnx_weights(
                onnx_path, config.hyper, n_vocab=n_vocab,
                n_speakers=config.num_speakers)
        elif any(stem.with_suffix(s).exists() for s in (".pt", ".ckpt", ".pth")):
            try:
                from .import_torch import import_torch_checkpoint
            except ImportError as e:
                raise FailedToLoadResource(
                    f"torch checkpoint import unavailable: {e}") from e
            ckpt = next(stem.with_suffix(s) for s in (".pt", ".ckpt", ".pth")
                        if stem.with_suffix(s).exists())
            params = import_torch_checkpoint(
                ckpt, config.hyper, n_vocab=n_vocab,
                n_speakers=config.num_speakers)
        else:
            raise FailedToLoadResource(
                f"no weights found next to {config_path} "
                f"(looked for {stem}.npz/.onnx/.pt/.ckpt)")
        return cls(config, params, **kwargs)

    @classmethod
    def random(cls, config: Optional[ModelConfig] = None, *, seed: int = 0,
               compute_dtype: Optional[str] = None,
               **config_overrides) -> "PiperVoice":
        """A randomly-initialized voice (tests, benchmarks, dry runs)."""
        if config is None:
            d = {
                "audio": {"sample_rate": 22050, "quality": "medium"},
                "num_speakers": 1,
                "espeak": {"voice": "en-us"},
                "phoneme_id_map": default_phoneme_id_map(),
            }
            d.update(config_overrides)
            d["num_symbols"] = len(d["phoneme_id_map"])
            config = ModelConfig.from_dict(d)
        n_vocab = config.num_symbols
        params = vits.init_vits(jax.random.PRNGKey(seed), config.hyper,
                                n_vocab=n_vocab,
                                n_speakers=config.num_speakers)
        return cls(config, params, seed=seed, compute_dtype=compute_dtype)

    def replica_for_device(self, device, *,
                           seed_offset: int = 0) -> "PiperVoice":
        """A copy of this voice pinned to one device (replica-pool serving).

        ``jax.device_put`` commits the params to ``device``; every jitted
        dispatch then runs on that chip (a committed operand places the
        whole computation), so N replicas built from one loaded voice
        occupy N chips with independent executables, RNG streams
        (``seed_offset`` keeps replica draws distinct), and jit caches —
        the isolation the pool's circuit breaker relies on.  Mutually
        exclusive with a mesh: a mesh makes all chips one SPMD dispatch,
        a pool makes each chip its own failure domain.
        """
        if self.mesh is not None:
            raise OperationError(
                "replica pools and device meshes are mutually exclusive "
                "(a mesh already spans the local chips as one dispatch)")
        params = jax.device_put(self.params, device)
        replica = PiperVoice(
            self.config, params, seed=self._seed + seed_offset,
            tashkeel=self._tashkeel,
            compute_dtype=("bfloat16" if self.compute_dtype is not None
                           else None),
            dispatch_policy=self._dispatch_policy,
            fused_epilogue=self.fused_epilogue,
            decode_quant=self.decode_quant or "off")
        replica.device = device
        replica.scope_voice = self.scope_voice
        return replica

    # ------------------------------------------------------------------
    # Model protocol
    # ------------------------------------------------------------------

    def audio_output_info(self) -> AudioInfo:
        return AudioInfo(sample_rate=self.config.sample_rate)

    def get_language(self) -> Optional[str]:
        return self.config.language or self.config.espeak_voice

    def get_speakers(self) -> Optional[dict[int, str]]:
        if not self.multi_speaker:
            return None
        return self.config.reversed_speaker_map()

    def properties(self) -> dict[str, str]:
        return {"quality": self.config.quality or "unknown"}

    def supports_streaming_output(self) -> bool:
        return True

    def get_default_synthesis_config(self) -> SynthesisConfig:
        return self.config.inference.copy()

    def get_fallback_synthesis_config(self) -> SynthesisConfig:
        with self._synth_lock:
            return self._synth_config.copy()

    def set_fallback_synthesis_config(self, config: Any) -> None:
        if not isinstance(config, SynthesisConfig):
            raise OperationError(
                "invalid synthesis config type "
                f"{type(config).__name__}")  # parity: Any-downcast failure
        with self._synth_lock:
            self._synth_config = config.copy()

    def phonemize_text(self, text: str) -> Phonemes:
        # Arabic: diacritize first (piper/src/lib.rs:253-258,270-281).
        # Digits expand to MSA number words BEFORE diacritization so the
        # inserted words receive harakat like any other Arabic word —
        # expanding after (in the normalizer) would feed the letter map
        # vowel-less consonant skeletons.
        if self._tashkeel is not None:
            with tracing.span("text-normalize", stage="tashkeel"):
                from ..text.rule_g2p import (
                    arabic_number_to_words, expand_numbers)

                text = expand_numbers(text, arabic_number_to_words)
                text = self._tashkeel.diacritize(text)
        return text_to_phonemes(
            text, voice=self.config.espeak_voice,
            remove_lang_switch_flags=True,
        )

    def _encode_phonemes(self, phonemes: str) -> list[int]:
        """Encode one sentence, feeding the voice's drop-rate diagnostics.

        Encoding behavior is reference-identical (unknown symbols dropped,
        piper/src/lib.rs:243); this wrapper only *counts* the drops and
        warns once per distinct symbol so a G2P/symbol-table mismatch is
        visible instead of silently degrading audio."""
        ids, dropped = self.config.phonemes_to_ids_diag(phonemes)
        stats = self.drop_stats
        stats["symbols_total"] += len(phonemes)
        if dropped:
            stats["symbols_dropped"] += len(dropped)
            for ch in dropped:
                stats["dropped"][ch] = stats["dropped"].get(ch, 0) + 1
                if ch not in self._warned_drops and not ch.isspace():
                    self._warned_drops.add(ch)
                    import logging

                    logging.getLogger("sonata").warning(
                        "phoneme %r (U+%04X) is not in this voice's "
                        "phoneme_id_map and was dropped at encoding",
                        ch, ord(ch))
        return ids

    def speak_one_sentence(self, phonemes: str) -> Audio:
        return self.speak_batch([phonemes])[0]

    # Representative prewarm texts: short / medium / long sentences cover
    # the common text buckets (and, batched together, the common group
    # shapes).
    _PREWARM_TEXTS = [
        "Hello there.",
        "This server compiles its executables before the first request.",
        "A longer sentence exercises the larger text and frame buckets so "
        "that real traffic arriving right after startup never waits on a "
        "fresh compilation of the synthesis pipeline.",
    ]

    def prewarm(self, texts: Optional[list[str]] = None, *,
                streaming: bool = False, chunk_size: int = 55,
                chunk_padding: int = 3) -> int:
        """Compile the common executables before serving traffic.

        A cold voice pays XLA compilation (tens of seconds per shape on a
        remote chip) on the first request that hits each (batch, text,
        frame) bucket; the reference has no equivalent because ONNX
        sessions are shape-polymorphic.  Synthesizes a representative
        batch until the executable cache stops growing, then compiles the
        neighbor frame buckets (the frame estimate rides each request's
        random duration draw, so traffic lands one bucket over routinely).
        With ``streaming=True`` also drains one realtime stream, warming
        the encoder/acoustics stages and the window decoders for the
        given chunk schedule.  Returns the number of compiled
        full-pipeline shapes.  Persistent caching pairs well with this
        (``jax_compilation_cache_dir``): after the first boot, prewarm
        mostly re-loads executables from disk.
        """
        phonemes = [p for t in (texts or self._PREWARM_TEXTS)
                    for p in self.phonemize_text(t)]
        if not phonemes:  # e.g. caller texts of pure punctuation
            return len(self._full_cache)
        for _ in range(4):
            n_compiled = len(self._full_cache)
            self.speak_batch(phonemes)
            if len(self._full_cache) == n_compiled:
                break
        self.prewarm_neighbor_buckets()
        if streaming:
            # one streamed drain per distinct text bucket: streaming
            # coverage must match the batch path's, or the first real
            # stream in an undrained bucket pays the cold encode mid-TTFB
            by_bucket: dict[int, str] = {}
            for p in phonemes:
                tb = bucket_for(len(self.config.phonemes_to_ids(p)),
                                TEXT_BUCKETS)
                if tb not in by_bucket or len(p) > len(by_bucket[tb]):
                    by_bucket[tb] = p
            for p in by_bucket.values():
                for _chunk in self.stream_synthesis(p, chunk_size,
                                                    chunk_padding):
                    pass
            self._prewarm_stream_batches()
        return len(self._full_cache)

    def _prewarm_stream_batches(self) -> None:
        """Compile the coalesced-batch window decoders for every streamed
        width warmed so far.

        Under concurrent load the stream coalescers pad every multi-request
        group to ONE canonical batch size — the executable set is exactly
        {b=1, b=max} per stage, never a graduated bucket ladder — so a
        sequential warmup (which only compiles b=1) leaves precisely one
        more shape per stage to warm here; without it the first wave of
        real concurrency pays one mid-request XLA compile per stage
        (measured: ~90x TTFB regression at 4 streams on a remote chip).
        Runs each shape once with dummy windows, blocking, so the
        executables are resident (and in the persistent cache) before
        traffic arrives.  Best-effort: a failing warm thunk (e.g. a
        sharding mismatch on an exotic mesh) must not abort serving.
        """
        from concurrent.futures import ThreadPoolExecutor

        with self._jit_lock:
            seen = [k for k in self._dec_cache if isinstance(k, tuple)
                    and k and k[0] in ("wbatch", "wfused")]
            enc_seen = [k for k in self._enc_cache]
            aco_seen = list(self._aco_cache)
        co = self._stream_decoder
        c = self.hp.inter_channels
        hop = self.hp.hop_length
        thunks = []
        # every width must be warm at BOTH canonical batch sizes: the
        # sequential drain itself coalesces its look-ahead windows, so a
        # width can enter the cache at b=max only — and the first lone
        # straggler at that width would then pay a b=1 cold compile
        # mid-request (the exact stall prewarm exists to prevent).
        # Iteration mode pads to the graduated ladder instead of the
        # canonical pair, so every rung up to max_batch warms.
        if isinstance(co, _IterationStreamDecoder):
            batch_set = {b for b in BATCH_BUCKETS if b <= co._max_batch}
        else:
            batch_set = {1, co._max_batch}
        # each variant (fused vs plain) warms wherever it was seen — a
        # fused-default voice drains streams through wfused shapes while
        # direct decode() callers may still touch wbatch ones
        widths = {(k[1], k[3], k[0] == "wfused") for k in seen}
        for (width, has_sid, fused) in widths:
            for b in batch_set:

                def warm_dec(width=width, b=b, has_sid=has_sid,
                             fused=fused):
                    args = [self.params, jnp.zeros((b, width, c),
                                                   jnp.float32)]
                    if fused:
                        fn = self._decode_windows_fused_fn(width, b,
                                                           has_sid)
                        args += [jnp.zeros((b,), jnp.int32),
                                 jnp.full((b,), width * hop, jnp.int32)]
                    else:
                        fn = self._decode_windows_batch_fn(width, b,
                                                           has_sid)
                    if has_sid:
                        args.append(jnp.zeros((b,), jnp.int32))
                    jax.block_until_ready(fn(*args))

                thunks.append(warm_dec)
        # the stage coalescer batches stream STARTS too: warm the b=max
        # encode/acoustics shapes it dispatches under concurrency.  Its
        # dispatch routes through _pad_batch, which can round the batch up
        # past max_batch to a multiple of the mesh data axis — derive the
        # warm batch through the same call or the warmed shape would never
        # match dispatch-time shapes on a non-dividing mesh.
        _, _, stage_b, _ = self._pad_batch(
            [[0]] * self._stream_stages._max_batch)
        # acoustics frame buckets ride the adaptive estimator, which keeps
        # refining between warm and real traffic — warm each seen bucket's
        # neighbors too, like prewarm_neighbor_buckets does for the fused
        # path, or the first post-warm stream lands one bucket over cold
        aco_targets = set(aco_seen)
        for fa in aco_seen:
            if fa in FRAME_BUCKETS:
                i = FRAME_BUCKETS.index(fa)
                aco_targets.add(FRAME_BUCKETS[max(i - 1, 0)])
                aco_targets.add(FRAME_BUCKETS[min(i + 1,
                                                  len(FRAME_BUCKETS) - 1)])
        for (eb, t) in enc_seen:
            # warm both the shape already seen (b=1 drains) and the
            # canonical coalesced-batch shape
            for b in {eb, stage_b}:

                def warm_stage(t=t, b=b):
                    ids = jnp.zeros((b, t), jnp.int32)
                    lens = jnp.ones((b,), jnp.int32)
                    nw = jnp.full((b,), 0.8, jnp.float32)
                    ls = jnp.ones((b,), jnp.float32)
                    ns = jnp.full((b,), 0.667, jnp.float32)
                    rng = jax.random.PRNGKey(0)
                    enc_args = [self.params, ids, lens, rng, nw, ls]
                    if self.multi_speaker:
                        enc_args.append(jnp.zeros((b,), jnp.int32))
                    out = self._encode_fn(b, t)(*enc_args)
                    m_p, logs_p, w_ceil, x_mask = jax.block_until_ready(out)
                    for fa in sorted(aco_targets):
                        aco_args = [self.params, m_p, logs_p, w_ceil,
                                    x_mask, rng, ns]
                        if self.multi_speaker:
                            aco_args.append(jnp.zeros((b,), jnp.int32))
                        jax.block_until_ready(
                            self._acoustics_fn(b, t, fa)(*aco_args))

                thunks.append(warm_stage)
        def best_effort(th):
            try:
                th()
            except Exception as e:  # warm failure must not abort serving
                import logging

                logging.getLogger("sonata").warning(
                    "prewarm thunk failed (continuing): %s", e)

        # compile concurrently: each thunk's first call blocks in XLA, and
        # the compiles for distinct shapes don't depend on each other —
        # 4 workers roughly quarter a cold boot's multi-minute warm
        with ThreadPoolExecutor(4, thread_name_prefix="sonata_warm") as ex:
            for res in ex.map(best_effort, thunks):
                pass

    def prewarm_neighbor_buckets(self) -> None:
        """Compile the frame buckets adjacent to every cached
        full-pipeline shape (one blocking :meth:`warm_shape` each — the
        single place the dummy-argument signature lives)."""
        from ..utils.buckets import FRAME_BUCKETS as _FB

        for (b, t, f) in list(self._full_cache):
            if f not in _FB:
                continue  # beyond-table bucket: no neighbor schedule
            i = _FB.index(f)
            for nf in {_FB[max(i - 1, 0)],
                       _FB[min(i + 1, len(_FB) - 1)]} - {f}:
                self.warm_shape((b, t, nf))

    # ------------------------------------------------------------------
    # bucket-lattice AOT warmup (serving/warmup.py drives this contract)
    # ------------------------------------------------------------------

    def lattice_shapes(self, mode: str = "full") -> list[tuple[int, int, int]]:
        """Enumerate the (batch, text, frame) shapes a restart must warm.

        The serving path compiles one executable per (b, t, f) bucket
        triple (:meth:`_full_fn`); this enumerates the triples real
        traffic can hit so the boot warmup compiles them *before*
        readiness instead of the first unlucky request paying the
        compile cliff (PR-4 measured cold 4556 ms vs cached 30 ms):

        - text axis: every :data:`TEXT_BUCKETS` entry (any sentence
          lands in one of them);
        - frame axis: the RANGE of buckets the live frame estimator
          can pick across the text bucket's id-length span (a sentence
          in bucket 128 may hold anywhere from 97 to 128 ids, and the
          frame estimate is linear in that length) — callers should
          run one *real* calibration utterance first so the estimator
          enumerates with an observed frames-per-id, not the
          cold-start prior — plus the next bucket UP in every mode
          (the estimator is a decaying upper bound that jumps up
          *immediately* on a higher observation, so the first
          post-warm sentence with a long duration draw lands there),
          plus the bucket below the range in ``full`` mode (slow
          downward decay under sustained traffic);
        - batch axis: 1 (sequential / per-request dispatch), plus, in
          ``full`` mode, the canonical coalesced batch the scheduler
          pads multi-request groups to (if coalescing is enabled —
          a CPU policy with max_batch 1 adds nothing).

        ``minimal`` is the batch-1, estimated-bucket-only subset —
        strictly contained in ``full``.  ``off`` returns [] (the
        caller keeps the legacy one-utterance warmup).  Ordered
        smallest-first so a budget expiry leaves the most common
        shapes warm.
        """
        if mode == "off":
            return []
        batches = {1}
        if mode == "full":
            try:
                kw = self.dispatch_policy.scheduler_kwargs()
                from ..utils.buckets import canonical_dispatch_batch

                canonical = canonical_dispatch_batch(kw["max_batch"])
            except Exception:  # policy probe failure must not block boot
                canonical = 1
            if canonical > 1:
                batches.add(canonical)
        ls = float(self.get_fallback_synthesis_config().length_scale)
        shapes: list[tuple[int, int, int]] = []
        n_fb = len(FRAME_BUCKETS)
        for ti, t in enumerate(TEXT_BUCKETS):
            # shortest and longest id counts that pad to this bucket
            lo_ids = TEXT_BUCKETS[ti - 1] + 1 if ti > 0 else 1
            f_lo = self._estimate_frame_bucket(lo_ids * max(ls, 0.05))
            f_hi = self._estimate_frame_bucket(t * max(ls, 0.05))
            frames = {f_lo, f_hi}
            if f_lo in FRAME_BUCKETS:
                i_lo = FRAME_BUCKETS.index(f_lo)
                # an f_hi past the table (bucket_for returns top-bucket
                # multiples there) still needs the reachable IN-TABLE
                # run warmed — clamping to the top keeps the range
                # covered instead of silently skipping it
                i_hi = (FRAME_BUCKETS.index(f_hi)
                        if f_hi in FRAME_BUCKETS else n_fb - 1)
                # the whole reachable range, plus one bucket up (the
                # estimator jumps up immediately on a higher
                # observation); full also covers one below (slow decay)
                if mode == "full":
                    i_lo = max(i_lo - 1, 0)
                frames.update(
                    FRAME_BUCKETS[i]
                    for i in range(i_lo, min(i_hi + 2, n_fb)))
            for b in sorted(batches):
                for f in sorted(frames):
                    shapes.append((b, t, f))
        shapes.sort(key=lambda s: (s[1], s[0], s[2]))
        shapes.extend(self._iteration_lattice_shapes(mode))
        return shapes

    def _iteration_lattice_shapes(self, mode: str) -> list:
        """Iteration-mode window-decoder shapes, appended to the lattice
        when ``SONATA_BATCH_MODE`` resolves to iteration.

        The persistent decode loop pads each iteration to the *graduated*
        batch ladder (1, 2, 4, ..., max) instead of dispatch mode's
        canonical {1, max} — that is where its padding-waste win comes
        from — so every rung x reachable window width must be warm or the
        first mid-occupancy iteration pays a cold compile the PR-9
        containment would rightly flag.  Tagged ``("wdec", width, batch,
        has_sid)`` tuples; :meth:`warm_shape` understands them.
        ``minimal`` keeps batch 1 only (single-resident-stream serving);
        iteration-mode deployments should warm ``full``.
        """
        try:
            policy = self.dispatch_policy
            if resolve_batch_mode(policy) != "iteration":
                return []
            kwargs = policy.stream_decode_kwargs()
        except Exception:  # policy probe failure must not block boot
            return []
        max_b = kwargs["max_batch"]
        if max_b <= 1:
            from ..utils.dispatch_policy import COALESCING_DEFAULTS

            max_b = COALESCING_DEFAULTS["stream_decode_max_batch"]
        ladder = [b for b in BATCH_BUCKETS if b <= max_b]
        if mode == "minimal":
            ladder = [1]
        # reachable widths: chunk windows bucket through FRAME_BUCKETS
        # and the chunk-growth schedule caps at 1024 frames plus padding,
        # so 1536 is the largest bucket a plan can produce
        widths = [w for w in FRAME_BUCKETS if w <= 1536]
        has_sid = bool(self.multi_speaker)
        return [("wdec", w, b, has_sid)
                for w in widths for b in ladder]

    def warm_shape(self, shape: tuple[int, int, int]) -> None:
        """Make one (b, t, f) full-pipeline shape hot before traffic.

        Preferred path is the **AOT executable store**
        (:func:`~sonata_tpu.utils.jax_cache.aot_cache_dir`): a prior
        boot's serialized executable loads in ~0.3 s with zero
        retracing; a cold shape compiles via
        ``jit(...).lower().compile()`` and serializes for the next
        boot.  Either way the executable is installed into
        ``_full_cache`` — the exact cache real traffic dispatches
        through (the compiled object is callable with the same
        arguments as the jitted function, and takes params as an
        argument, so one blob serves every voice with these dims).
        Falls back to a dummy-argument jit call (which rides JAX's own
        persistent compile cache) when AOT is disabled, a mesh is
        attached, or anything in the AOT path fails.  Bypasses
        :meth:`_infer_batch` on purpose: dummy zeros must never feed
        :meth:`_observe_frames`, or warmup would corrupt the frame
        estimator the lattice was enumerated with.

        Iteration-mode shapes (``("wdec", width, batch, has_sid)`` from
        :meth:`_iteration_lattice_shapes`) compile the batched window
        decoder directly — a plain jit warm riding the persistent
        compile cache (no AOT store: the decoder program is small and
        retraces in well under a second).
        """
        if shape and shape[0] == "wdec":
            _tag, width, b, has_sid = shape
            # warm the variant real traffic dispatches through: the
            # fused decode+epilogue program when SONATA_FUSED_EPILOGUE
            # is on (the default), the plain window decoder otherwise —
            # warming the wrong one would leave every live iteration
            # cold and trip the PR-9 containment
            fused = self.fused_epilogue != "off"
            args = [self.params,
                    jnp.zeros((b, width, self.hp.inter_channels),
                              jnp.float32)]
            if fused:
                fn = self._decode_windows_fused_fn(width, b, has_sid)
                hop = self.hp.hop_length
                args += [jnp.zeros((b,), jnp.int32),
                         jnp.full((b,), width * hop, jnp.int32)]
            else:
                fn = self._decode_windows_batch_fn(width, b, has_sid)
            if has_sid:
                args.append(jnp.zeros((b,), jnp.int32))
            jax.block_until_ready(fn(*args))
            return
        b, t, f = shape
        with self._jit_lock:
            if (b, t, f) in self._full_cache:
                return  # already hot (traffic or an earlier warm)
        args = self._dummy_full_args(b, t)
        if self.mesh is None:
            from ..utils.jax_cache import aot_cache_dir

            aot_dir = aot_cache_dir()
            if aot_dir is not None:
                try:
                    if self._warm_shape_aot(shape, args, aot_dir):
                        return
                except Exception as e:
                    import logging

                    logging.getLogger("sonata").warning(
                        "AOT warm of %s failed (%s); falling back to "
                        "jit warmup", shape, e)
        fn = self._full_fn(b, t, f)
        jax.block_until_ready(fn(*args))

    def _dummy_full_args(self, b: int, t: int) -> list:
        """The canonical zero-valued argument list for a (b, t, *)
        full-pipeline executable — the ONE place the warm/prewarm dummy
        signature lives."""
        args = [self.params,
                jnp.zeros((b, t), jnp.int32),
                jnp.ones((b,), jnp.int32),
                jax.random.PRNGKey(0),
                jnp.full((b,), 0.8, jnp.float32),
                jnp.ones((b,), jnp.float32),
                jnp.full((b,), 0.667, jnp.float32)]
        if self.multi_speaker:
            args.append(jnp.zeros((b,), jnp.int32))
        return args

    def _aot_key(self, shape: tuple[int, int, int]) -> str:
        """Cache key for one serialized executable: everything that
        changes the compiled program — jax version, backend, target
        device (a replica's executable is placed on ITS chip), model
        dims, vocab/speaker counts, compute dtype, and the shape.
        Params are an *argument* of the executable, so voices sharing
        dims share blobs."""
        device = getattr(self, "device", None)
        parts = (jax.__version__, jax.default_backend(), str(device),
                 repr(sorted(vars(self.hp).items())),
                 self.config.num_symbols, self.config.num_speakers,
                 str(self.compute_dtype), bool(self.multi_speaker),
                 str(self.decode_quant), tuple(shape))
        return hashlib.blake2b(repr(parts).encode(),
                               digest_size=16).hexdigest()

    def _warm_shape_aot(self, shape: tuple[int, int, int], args: list,
                        aot_dir: str) -> bool:
        """Load (or build + serialize) one shape's AOT executable and
        install it in ``_full_cache``.  Concurrent writers race safely
        (atomic tmp + rename); a corrupt blob raises and the caller
        falls back to the jit path."""
        import pickle

        from jax.experimental.serialize_executable import (
            deserialize_and_load,
            serialize,
        )

        b, t, f = shape
        path = os.path.join(aot_dir, self._aot_key(shape) + ".aotx")
        if os.path.exists(path):
            with open(path, "rb") as fh:
                payload, in_tree, out_tree = pickle.load(fh)
            executable = deserialize_and_load(payload, in_tree, out_tree)
        else:
            fn = self._full_fn(b, t, f)
            executable = fn.lower(*args).compile()
            tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as fh:
                pickle.dump(serialize(executable), fh)
            os.replace(tmp, path)
        with self._jit_lock:
            self._full_cache[(b, t, f)] = executable
        return True

    # Cap on rows per device dispatch: beyond this, padding waste and
    # compile sizes grow without amortizing any more fixed latency.
    MAX_DISPATCH_BATCH = 64
    # Floor on rows per dispatch when splitting a batch for pipelining:
    # below this, per-dispatch fixed cost (host-link round trip + program
    # launch) dominates — measured 4x4-row dispatches at 2.5x the wall
    # time of 2x8 on a tunneled v5e.
    MIN_DISPATCH_BATCH = 8
    # Device programs kept in flight during pipelined batch synthesis.
    PIPELINE_DEPTH = 3

    def speak_batch(self, phoneme_batches: list[str],
                    speakers: Optional[list[Optional[int]]] = None,
                    scales: "Optional[list[Optional[SynthesisConfig]]]"
                    = None) -> list[Audio]:
        """True batched synthesis on the device.

        Large corpora are partitioned by text-length bucket (so a 1k-line
        corpus doesn't pad every sentence to the longest one) and chunked
        to :data:`MAX_DISPATCH_BATCH` rows per dispatch; results reassemble
        in input order.

        ``speakers``: optional per-sentence speaker ids (None entries fall
        back to the config speaker) — different speakers can share one
        device dispatch, which is what lets the continuous-batching
        scheduler coalesce requests from different voices' speakers.
        """
        if not phoneme_batches:
            return []
        sc = self.get_fallback_synthesis_config()
        with tracing.span("encode-ids") as sp:
            ids_list = [self._encode_phonemes(p) for p in phoneme_batches]
            sp.annotate(sentences=len(ids_list))
        n = len(ids_list)
        if speakers is not None and len(speakers) != n:
            raise OperationError(
                f"speakers list has {len(speakers)} entries for {n} sentences")
        if scales is not None and len(scales) != n:
            raise OperationError(
                f"scales list has {len(scales)} entries for {n} sentences")

        chunks = self._plan_dispatch_groups(ids_list, sc, scales)

        # Pipelined dispatch: enqueue up to PIPELINE_DEPTH device programs
        # ahead, then fetch in order.  The chip computes group k+1 while
        # group k's result streams back over the (high-latency, when the
        # chip is remote) host link — measured ~20% per-batch win on a
        # tunneled v5e even for a 16-sentence batch split in two.
        wavs: list[Optional[np.ndarray]] = [None] * n
        lengths = [0] * n
        row_ms = [0.0] * n
        pending: list[tuple[list[int], Any]] = []
        gi = 0

        t_last_drain = time.perf_counter()

        def drain_one():
            nonlocal t_last_drain
            chunk, ticket = pending.pop(0)
            w, wl = self._finish_batch(ticket)
            # honest per-dispatch timing: each row carries the wall time
            # attributable to the dispatch that produced it, amortized over
            # that dispatch's rows — not the whole batch's average (the
            # reference times each session.run, piper/src/lib.rs:361-380).
            # With pipelining the device runs dispatches serially, so a
            # ticket's interval starts at the later of its enqueue and the
            # previous drain — raw enqueue→result would double-count the
            # queue wait behind earlier in-flight groups.
            now = time.perf_counter()
            ms = (now - max(ticket["t_enqueue"], t_last_drain)) * 1000.0
            t_last_drain = now
            ms /= len(chunk)
            for row, i in enumerate(chunk):
                wavs[i] = w[row]
                lengths[i] = int(wl[row])
                row_ms[i] = ms

        # direct callers (no scheduler) get their device work as a
        # "dispatch" span too; under the batch scheduler this is a no-op
        # (the worker thread carries no trace context — the scheduler
        # records the shared dispatch span itself)
        with tracing.span("dispatch", sentences=n, groups=len(chunks)):
            while gi < len(chunks) or pending:
                # until the frame estimator has a real observation, keep
                # one dispatch in flight: a cold underestimate would
                # otherwise clip every in-flight group and pay an overflow
                # rerun for each, instead of the documented single
                # first-batch retry
                depth = self.PIPELINE_DEPTH if self._fpi_observed else 1
                while gi < len(chunks) and len(pending) < depth:
                    chunk = chunks[gi]
                    gi += 1
                    ticket = self._enqueue_batch(
                        [ids_list[i] for i in chunk], sc,
                        speakers=([speakers[i] for i in chunk]
                                  if speakers is not None else None),
                        scales=([scales[i] for i in chunk]
                                if scales is not None else None))
                    pending.append((chunk, ticket))
                drain_one()

        info = self.audio_output_info()
        return [
            Audio(AudioSamples(np.asarray(wavs[i][: lengths[i]])), info,
                  inference_ms=row_ms[i])
            for i in range(n)
        ]

    def _plan_dispatch_groups(self, ids_list: list[list[int]],
                              sc: SynthesisConfig,
                              scales=None) -> list[list[int]]:
        """Partition sentence indices into device-dispatch groups.

        Rows sort by estimated frame count, then split into contiguous
        groups whose sizes are exact batch buckets (zero dummy rows — a
        dummy row still ships a full frame-bucket window of samples back
        over the host link).  Group sizes cap at half the batch (min 8)
        so at least two dispatches pipeline compute against result
        transfer; sorted order keeps each group's frame bucket tight.
        """
        n = len(ids_list)

        def est_frames(i) -> float:
            # relative frame driver per row; the shared frames-per-id
            # factor cancels in a sort, so it stays out of the key
            ls = (scales[i].length_scale
                  if scales is not None and i < len(scales)
                  and scales[i] is not None else sc.length_scale)
            return len(ids_list[i]) * max(float(ls), 0.05)

        def split_by_text_bucket(group: list[int]) -> list[list[int]]:
            """Split where a row's text bucket jumps past 2x the current
            subgroup head's (re-based per subgroup — a 16→64→512 tier mix
            splits twice): a frame-alike but text-length-wild mix (possible
            with per-row length_scale overrides) would otherwise pad every
            short row's text — and, worse, its frame-bucket transfer
            window — to the outlier's size.  Same rule the pre-pipelining
            packer applied; off-bucket subgroup sizes just pad a few dummy
            rows."""
            out: list[list[int]] = []
            for i in group:
                tb = bucket_for(len(ids_list[i]), TEXT_BUCKETS)
                if not out or tb > 2 * bucket_for(
                        len(ids_list[out[-1][0]]), TEXT_BUCKETS):
                    out.append([i])
                else:
                    out[-1].append(i)
            return out

        order = sorted(range(n), key=est_frames)
        if n < 2 * self.MIN_DISPATCH_BATCH:
            return split_by_text_bucket(order)
        # cap a group at half the batch (bucket-rounded down) so there are
        # always ≥2 dispatches to pipeline; never below MIN or above MAX
        half = max((n + 1) // 2, self.MIN_DISPATCH_BATCH)
        cap = next(s for s in reversed(BATCH_BUCKETS) if s <= half)
        cap = min(cap, self.MAX_DISPATCH_BATCH)
        # decompose n into bucket sizes ≤ cap, smallest group first so the
        # leftover (non-power-of-two) rows are the *short* ones
        sizes: list[int] = []
        rest = n
        while rest:
            take = min(cap, rest)
            sizes.append(next((s for s in reversed(BATCH_BUCKETS)
                               if s <= take), BATCH_BUCKETS[0]))
            rest -= sizes[-1]
        sizes.sort()
        # a leftover smaller than MIN rides inside the next group as extra
        # rows — but only while the merged group stays near its batch
        # bucket: a few padding dummies cost less than a tiny dispatch's
        # full host-link round trip, a few dozen cost more
        while len(sizes) > 1 and sizes[0] < self.MIN_DISPATCH_BATCH:
            merged = sizes[0] + sizes[1]
            if (merged > self.MAX_DISPATCH_BATCH
                    or bucket_for(merged, BATCH_BUCKETS) - merged
                    > self.MIN_DISPATCH_BATCH):
                break
            small = sizes.pop(0)
            sizes[0] += small
        groups, pos = [], 0
        for s in sizes:
            groups.extend(split_by_text_bucket(order[pos:pos + s]))
            pos += s
        return groups

    # ------------------------------------------------------------------
    # staged inference
    # ------------------------------------------------------------------

    def _next_rng(self):
        with self._rng_lock:
            self._rng_counter += 1
            counter = self._rng_counter
        mixed = (self._seed * 0x9E3779B1 + counter) & 0xFFFFFFFF
        return jax.random.PRNGKey(np.uint32(mixed))

    def _scale_arrays(self, sc: SynthesisConfig, batch: int,
                      scales: "Optional[list[Optional[SynthesisConfig]]]"
                      = None):
        """Per-row (noise_w, length_scale, noise_scale) [B] arrays.

        ``scales`` entries override the shared config row-wise, letting a
        coalesced batch carry each request's own synthesis scales."""
        def row(i, attr):
            if scales is not None and i < len(scales) and scales[i] is not None:
                return float(getattr(scales[i], attr))
            return float(getattr(sc, attr))

        nw = [row(i, "noise_w") for i in range(batch)]
        ls = [row(i, "length_scale") for i in range(batch)]
        ns = [row(i, "noise_scale") for i in range(batch)]
        # host lists returned alongside the device arrays so callers can do
        # host-side math (frame estimation) without a device round trip
        return (jnp.asarray(nw, jnp.float32), jnp.asarray(ls, jnp.float32),
                jnp.asarray(ns, jnp.float32), ls)

    def _sid_array(self, sc: SynthesisConfig, batch: int,
                   speakers: Optional[list[Optional[int]]] = None):
        if not self.multi_speaker:
            # single-speaker voice: only speaker 0 (or None) is honorable —
            # silently producing default-voice audio for another id would
            # hide a caller bug
            for sid in speakers or []:
                if sid not in (None, 0):
                    raise OperationError(
                        f"speaker id {sid} requested on a single-speaker "
                        "voice")
            return None
        default = sc.speaker[1] if sc.speaker else 0
        rows = [default if s is None else s
                for s in (speakers or [])] or [default]
        rows = rows + [default] * (batch - len(rows))
        for sid in rows:
            if not 0 <= sid < self.config.num_speakers:
                # JAX gather would silently clamp an out-of-range id
                raise OperationError(
                    f"speaker id {sid} out of range "
                    f"(voice has {self.config.num_speakers} speakers)")
        return jnp.asarray(rows[:batch], dtype=jnp.int32)

    def _jit(self, run, batch_args: tuple[int, ...]):
        """jit, adding mesh shardings when a mesh is attached.

        ``batch_args``: positional indices of [B, ...]-shaped arguments
        (sharded on the data axis).  Params, RNG keys, and scalars are
        replicated; every output is batch-major and data-sharded.  XLA then
        runs the whole stage SPMD across chips with no code changes — this
        is the TPU counterpart of the reference's rayon fan-out
        (``synth/src/lib.rs:316-320``).
        """
        if self.mesh is None:
            return jax.jit(run)
        import inspect

        from ..parallel.mesh import (
            data_sharding, param_shardings, replicated)

        ds, rep = data_sharding(self.mesh), replicated(self.mesh)
        # arg 0 is always the params pytree: its per-leaf shardings carry
        # the tensor-parallel decoder annotations (model axis); plain
        # replication when model_parallel == 1
        ps = param_shardings(self.mesh, self.params)
        n_args = len(inspect.signature(run).parameters)
        in_shardings = tuple(
            ps if i == 0 else (ds if i in batch_args else rep)
            for i in range(n_args))
        return jax.jit(run, in_shardings=in_shardings, out_shardings=ds)

    def _encode_fn(self, b: int, t: int):
        """Jitted stage 1 for batch/text bucket (b, t)."""
        key = (b, t)
        with self._jit_lock:
            fn = self._enc_cache.get(key)
            if fn is None:
                hp = self.hp

                mesh = self.mesh  # seq>1 ⇒ ring-attention text encoder

                if self.multi_speaker:
                    def run(params, ids, lens, rng, noise_w, length_scale, sid):
                        m_p, logs_p, w_ceil, x_mask, _ = vits.encode_text(
                            params, hp, ids, lens, rng, noise_w=noise_w,
                            length_scale=length_scale, sid=sid, mesh=mesh)
                        return m_p, logs_p, w_ceil, x_mask
                else:
                    def run(params, ids, lens, rng, noise_w, length_scale):
                        m_p, logs_p, w_ceil, x_mask, _ = vits.encode_text(
                            params, hp, ids, lens, rng, noise_w=noise_w,
                            length_scale=length_scale, mesh=mesh)
                        return m_p, logs_p, w_ceil, x_mask

                batch = ((1, 2, 4, 5, 6) if self.multi_speaker
                         else (1, 2, 4, 5))
                fn = self._jit(run, batch)
                self._enc_cache[key] = fn
        return fn

    @staticmethod
    def _decode_quantize(params, hp, z, y_lengths, g, mesh=None,
                         compute_dtype=None):
        """HiFi-GAN decode + on-device peak-scaled i16 quantization.

        i16 quarters the host transfer, which dominates when the chip sits
        behind a network link.  The per-row peak ships back too so the host
        restores original amplitudes — relative loudness across sentences is
        preserved, and the final WAV write still applies the reference's
        single global normalization (samples.rs:51-75).

        The single definition of the quantization contract — every path that
        decodes a full batch goes through here.
        """
        wav = vits.decode(params, hp, z, g=g, mesh=mesh,
                          compute_dtype=compute_dtype)
        wav_lengths = y_lengths * hp.hop_length
        valid = (jnp.arange(wav.shape[1])[None, :] < wav_lengths[:, None])
        peak = jnp.max(jnp.abs(wav) * valid, axis=1, keepdims=True)
        scale = 32767.0 / jnp.maximum(peak, 0.01)
        wav_i16 = jnp.clip(wav * scale, -32768.0, 32767.0).astype(jnp.int16)
        return wav_i16, wav_lengths, peak[:, 0]

    def _acoustics_fn(self, b: int, t: int, f: int):
        """Jitted stage 2 alone (streaming path: keep z on device)."""
        with self._jit_lock:
            fn = self._aco_cache.get(f)
            if fn is None:
                hp = self.hp
                max_frames = f
                mesh = self.mesh

                def body(params, m_p, logs_p, w_ceil, x_mask, rng,
                         noise_scale, g):
                    z, y_mask, y_lengths = vits.acoustics(
                        params, hp, m_p, logs_p, w_ceil, x_mask, rng,
                        noise_scale=noise_scale, max_frames=max_frames, g=g,
                        mesh=mesh)
                    return z, y_lengths

                # signature arity must match the call exactly so that mesh
                # in_shardings line up positionally
                if self.multi_speaker:
                    def run(params, m_p, logs_p, w_ceil, x_mask, rng,
                            noise_scale, sid):
                        g = params["emb_g"][sid][:, None, :]
                        return body(params, m_p, logs_p, w_ceil, x_mask, rng,
                                    noise_scale, g)

                    batch = (1, 2, 3, 4, 6, 7)
                else:
                    def run(params, m_p, logs_p, w_ceil, x_mask, rng,
                            noise_scale):
                        return body(params, m_p, logs_p, w_ceil, x_mask, rng,
                                    noise_scale, None)

                    batch = (1, 2, 3, 4, 6)
                fn = self._jit(run, batch)
                self._aco_cache[f] = fn
        return fn

    def _full_fn(self, b: int, t: int, f: int):
        """Single-dispatch batch pipeline: ids → int16 audio.

        The compute for a whole batch is well under a millisecond on a TPU
        chip; batched latency is round trips.  This path does encode +
        acoustics + decode + quantization in ONE device program with a
        *statically estimated* frame budget, so a batch costs exactly one
        dispatch and one result transfer — no frame-count host sync.  The
        caller checks the returned per-row frame requirement and retries
        with a bigger bucket on (rare) overflow.
        """
        key = (b, t, f)
        with self._jit_lock:
            fn = self._full_cache.get(key)
            if fn is None:
                hp = self.hp
                max_frames = f

                mesh = self.mesh  # seq>1 ⇒ ring-attention text encoder
                cdt = self.compute_dtype

                def body(params, ids, lens, rng, noise_w, length_scale,
                         noise_scale, sid):
                    rng_dur, rng_noise = jax.random.split(rng)
                    m_p, logs_p, w_ceil, x_mask, g = vits.encode_text(
                        params, hp, ids, lens, rng_dur, noise_w=noise_w,
                        length_scale=length_scale, sid=sid, mesh=mesh)
                    frames_needed = jnp.sum(w_ceil, axis=1).astype(jnp.int32)
                    z, y_mask, y_lengths = vits.acoustics(
                        params, hp, m_p, logs_p, w_ceil, x_mask, rng_noise,
                        noise_scale=noise_scale, max_frames=max_frames, g=g,
                        mesh=mesh)
                    wav_i16, wav_lengths, peaks = self._decode_quantize(
                        params, hp, z, y_lengths, g, mesh=mesh,
                        compute_dtype=cdt)
                    return wav_i16, wav_lengths, peaks, frames_needed

                if self.multi_speaker:
                    def run(params, ids, lens, rng, noise_w, length_scale,
                            noise_scale, sid):
                        return body(params, ids, lens, rng, noise_w,
                                    length_scale, noise_scale, sid)

                    batch = (1, 2, 4, 5, 6, 7)
                else:
                    def run(params, ids, lens, rng, noise_w, length_scale,
                            noise_scale):
                        return body(params, ids, lens, rng, noise_w,
                                    length_scale, noise_scale, None)

                    batch = (1, 2, 4, 5, 6)
                fn = self._jit(run, batch)
                self._full_cache[key] = fn
        return fn

    def _decode_window_fn(self, width: int):
        """Jitted chunk decoder: z window of static ``width`` → samples."""
        key = width
        with self._jit_lock:
            fn = self._dec_cache.get(key)
            if fn is None:
                hp = self.hp
                cdt = self.compute_dtype

                def run(params, z, start, sid=None):
                    g = (params["emb_g"][sid][:, None, :]
                         if sid is not None else None)
                    window = jax.lax.dynamic_slice_in_dim(z, start, width,
                                                          axis=1)
                    return vits.decode(params, hp, window, g=g,
                                       compute_dtype=cdt)

                fn = jax.jit(run)
                self._dec_cache[key] = fn
        return fn

    def _decode_windows_batch_fn(self, width: int, b: int, has_sid: bool):
        """Jitted batched chunk decoder for coalesced concurrent streams:
        stacked pre-sliced z windows [B, width, C] → [B, width*hop].

        Windows are sliced out of each stream's z *before* they reach this
        function (coalescer ``submit``), so the executable's shape depends
        only on (width, b, has_sid) — NOT on each utterance's frame
        bucket.  That keeps the compiled-shape set small and fully
        prewarmable; the first round of concurrent traffic must never pay
        a mid-request XLA compile (measured: a cold b=4 shape on a remote
        chip stalled every stream's first chunk by tens of seconds)."""
        # the stacked [B, width, C] windows buffer is dead after the call,
        # but XLA input/output aliasing needs an identically-sized output
        # to reuse it and the [B, width*hop] waveform never matches — the
        # annotation only produced per-compile "donated buffers were not
        # usable" warnings (r05 streaming bench), so donation is now off
        # unless SONATA_DONATE=1 forces it back on for A/B runs.
        donate = should_donate()
        key = ("wbatch", width, b, has_sid, donate)
        with self._jit_lock:
            fn = self._dec_cache.get(key)
            if fn is None:
                hp = self.hp
                cdt = self.compute_dtype

                def run(params, windows, sid=None):
                    g = (params["emb_g"][sid][:, None, :]
                         if sid is not None else None)
                    return vits.decode(params, hp, windows, g=g,
                                       compute_dtype=cdt)

                fn = jax.jit(run, donate_argnums=(1,) if donate else ())
                self._dec_cache[key] = fn
        return fn

    def _decode_windows_fused_fn(self, width: int, b: int, has_sid: bool):
        """Fused-epilogue variant of :meth:`_decode_windows_batch_fn`
        (``SONATA_FUSED_EPILOGUE=lax|pallas``): window decode +
        crossfade taper + peak-scaled i16 quantize as ONE device
        program.

        Extra args ``lo``/``hi`` [B] are each row's emitted sample range
        (value-dynamic, shape-static — the executable set stays one per
        (width, batch rung), exactly like the unfused fn, so the warmup
        lattice covers it).  Returns (i16 [B, width*hop], peak [B]); the
        host dequantizes and slices instead of tapering — the per-chunk
        epilogue leaves the TTFB path, and the result transfer halves
        (i16 + per-row peak instead of f32)."""
        mode = self.fused_epilogue
        key = ("wfused", width, b, has_sid, mode)
        with self._jit_lock:
            fn = self._dec_cache.get(key)
            if fn is None:
                hp = self.hp
                cdt = self.compute_dtype

                def run(params, windows, lo, hi, sid=None):
                    g = (params["emb_g"][sid][:, None, :]
                         if sid is not None else None)
                    wav = vits.decode(params, hp, windows, g=g,
                                      compute_dtype=cdt)
                    return decode_opts.fused_epilogue(
                        wav, lo, hi, CROSSFADE_SAMPLES, mode=mode)

                fn = jax.jit(run)
                self._dec_cache[key] = fn
        return fn

    def _wdec_cache_key(self, width: int, b: int, has_sid: bool,
                        fused: Optional[bool] = None) -> tuple:
        """The decode-cache key live window-decode traffic dispatches
        through for this (width, batch, sid) shape — fused when the
        epilogue arm is on (the default), the plain batch decoder
        otherwise.  The single place warmup, attribution, and tests
        resolve the active variant."""
        if fused is None:
            fused = self.fused_epilogue != "off"
        if fused:
            return ("wfused", width, b, has_sid, self.fused_epilogue)
        return ("wbatch", width, b, has_sid, should_donate())

    @property
    def dispatch_policy(self) -> DispatchPolicy:
        """The resolved backend-adaptive dispatch policy (lazy, cached).

        Resolution order: an explicitly-passed policy > env overrides
        (``SONATA_STREAM_COALESCE``, ``SONATA_DISPATCH_POLICY``) > the
        backend fast path / cached dispatch-scaling probe — see
        :func:`sonata_tpu.utils.dispatch_policy.resolve_policy`.
        Resolved outside the jit lock: the probe may itself dispatch.
        """
        with self._policy_lock:
            if self._dispatch_policy is None:
                self._dispatch_policy = resolve_policy(
                    shape_key=(self.hp.inter_channels, self.hp.hop_length))
                import logging

                logging.getLogger("sonata").info(
                    self._dispatch_policy.describe())
            return self._dispatch_policy

    def dispatch_stats(self) -> dict:
        """Per-dispatch observability: the policy decision plus each
        stream coalescer's request/dispatch counters and coalescing
        ratio (requests per device dispatch; 1.0 = no coalescing).
        Stages that never ran report ``None``."""
        def view(co):
            if co is None:
                return None
            s = dict(co.stats)
            s["coalescing_ratio"] = round(
                s["requests"] / max(s["dispatches"], 1), 3)
            return s

        with self._jit_lock:
            decode, stage = self._stream_coalescer, self._stage_coalescer
            iteration = self._iter_decoder
        pol = self._dispatch_policy
        try:
            mode = resolve_batch_mode(pol)
        except OperationError:
            mode = None  # typo'd SONATA_BATCH_MODE fails at stream time
        return {"policy": pol.as_dict() if pol is not None else None,
                "batch_mode": mode,
                "stream_decode": view(decode),
                "stream_stage": view(stage),
                "iteration": view(iteration)}

    @property
    def _stream_decoder(self):
        """The active window-decode engine for NEW streams.

        ``SONATA_BATCH_MODE`` (default: iteration iff the PR-1 dispatch
        policy kept coalescing) picks between the dispatch-granular
        coalescer and the persistent iteration loop; the degradation
        ladder can force iteration back to dispatch at level >= 1
        (consulted per stream, so recovery re-admits the loop with no
        restart).  Both engines can exist at once — streams resident in
        the loop finish there while degraded traffic takes the wave
        path."""
        policy = self.dispatch_policy
        mode = effective_batch_mode(policy)
        kwargs = policy.stream_decode_kwargs()
        with self._jit_lock:
            if self._voice_closed:
                raise OperationError(
                    "voice is closed; streaming is unavailable")
            if mode == "iteration":
                if self._iter_decoder is None:
                    # an env-forced iteration mode on a per-request
                    # policy (batch 1) still wants a real batch axis —
                    # the loop exists to share iterations across
                    # streams, so take the canonical coalescing batch
                    b = kwargs["max_batch"]
                    if b <= 1:
                        from ..utils.dispatch_policy import (
                            COALESCING_DEFAULTS)

                        b = COALESCING_DEFAULTS["stream_decode_max_batch"]
                    self._iter_decoder = _IterationStreamDecoder(
                        self, max_batch=b)
                return self._iter_decoder
            if self._stream_coalescer is None:
                self._stream_coalescer = _StreamDecodeCoalescer(
                    self, **kwargs)
            return self._stream_coalescer

    @property
    def _stream_stages(self) -> "_StreamStageCoalescer":
        kwargs = self.dispatch_policy.stream_stage_kwargs()
        with self._jit_lock:
            if self._voice_closed:
                raise OperationError(
                    "voice is closed; streaming is unavailable")
            if self._stage_coalescer is None:
                self._stage_coalescer = _StreamStageCoalescer(
                    self, **kwargs)
            return self._stage_coalescer

    def start_draining(self) -> None:
        """Graceful-drain hook (the frontends call this alongside
        ``ReplicaPool.start_draining`` before voice teardown): the
        iteration loop stops admitting NEW stream joins — refused typed
        ``draining`` — while resident streams keep their riders until
        they finish; the loop then exits at an iteration boundary.  The
        dispatch-mode coalescers need no equivalent (they hold no
        resident state; close() drains them).  Idempotent."""
        with self._jit_lock:
            iteration = self._iter_decoder
        if iteration is not None:
            iteration.start_draining()

    def close(self) -> None:
        """Unload the voice: stop the coalescer threads and fail their
        queued work.

        The reference's `libsonataUnloadSonataVoice`
        (``capi/src/lib.rs:228``) drops the model; here the voice also
        owns four lazily-spawned daemon threads, which without an explicit
        close linger up to one 5 s poll interval after the last reference
        drops.  Idempotent; a closed voice can still synthesize
        non-streaming batches (the coalescers are streaming-only), but
        any further STREAMING raises OperationError — close() is terminal
        for the coalescers, never respawning their threads."""
        with self._jit_lock:
            self._voice_closed = True
            decoder, self._stream_coalescer = self._stream_coalescer, None
            stages, self._stage_coalescer = self._stage_coalescer, None
            iteration, self._iter_decoder = self._iter_decoder, None
        if decoder is not None:
            decoder.close()
        if stages is not None:
            stages.close()
        if iteration is not None:
            iteration.close()

    def _pad_batch(self, ids_list: list[list[int]]):
        """Pad a sentence batch to (batch, text) buckets.

        Both axes are bucketed so the number of compiled executables stays
        bounded under arbitrary workloads; dummy rows are masked out by
        their length-1 semantics and dropped by callers.  With a mesh
        attached, the batch rounds up to a multiple of the data-axis size
        so it shards evenly on any mesh (including non-power-of-two).
        """
        n_real = len(ids_list)
        b = bucket_for(n_real, BATCH_BUCKETS)
        if self.mesh is not None:
            from ..parallel.mesh import DATA_AXIS

            d = self.mesh.shape[DATA_AXIS]
            b = ((max(b, d) + d - 1) // d) * d
        t = bucket_for(max(len(i) for i in ids_list), TEXT_BUCKETS)
        padded = ids_list + [[0]] * (b - n_real)
        ids = jnp.asarray([pad_to(i, t) for i in padded], dtype=jnp.int32)
        lens = jnp.asarray([len(i) for i in ids_list] + [1] * (b - n_real),
                           dtype=jnp.int32)
        return ids, lens, b, t

    def _run_encode(self, ids_list: list[list[int]], sc: SynthesisConfig):
        """Run stage 1 on a padded batch (streaming path)."""
        ids, lens, b, t = self._pad_batch(ids_list)
        sid = self._sid_array(sc, b)
        nw, ls, _, _ = self._scale_arrays(sc, b)
        args = [self.params, ids, lens, self._next_rng(), nw, ls]
        if sid is not None:
            args.append(sid)
        m_p, logs_p, w_ceil, x_mask = self._encode_fn(b, t)(*args)
        return m_p, logs_p, w_ceil, x_mask, sid, b, t

    def _estimate_frame_bucket(self, weighted_ids: float) -> int:
        """``weighted_ids``: max over rows of ``len(ids) * length_scale`` —
        the true per-row frame driver (a batch mixing a long 1x row with a
        short 3x row must not be budgeted as long × 3x)."""
        with self._fpi_lock:
            fpi = self._frames_per_id
        # fpi is itself a decaying UPPER bound over observed ratios, so the
        # safety multiplier stays small: 1.25 stacked a second layer of
        # headroom on top and pushed typical batches a whole frame bucket
        # up — every row then ships a ~2x transfer window back to the
        # host.  Underestimates are caught and cost one (rare) retry.
        est = weighted_ids * fpi * 1.08
        return bucket_for(max(int(est), 1), FRAME_BUCKETS)

    def _observe_frames(self, weighted_ids: float, frames: int) -> None:
        ratio = frames / max(weighted_ids, 1.0)
        with self._fpi_lock:
            if not self._fpi_observed:
                # first real observation replaces the cold-start prior —
                # decaying down from a too-high prior at 0.5% per batch
                # would overshoot the frame bucket (and its per-row
                # transfer window) for hundreds of batches.  A 15% margin
                # guards the pipelined groups dispatched right after this
                # single sample: one low draw must not set a bound that
                # makes every in-flight group overflow and rerun
                self._frames_per_id = ratio * 1.15
            else:
                # decaying upper bound: shrinks slowly, jumps up immediately
                self._frames_per_id = max(self._frames_per_id * 0.995, ratio)
            self._fpi_observed = True

    def _infer_batch(self, ids_list: list[list[int]], sc: SynthesisConfig,
                     speakers: Optional[list[Optional[int]]] = None,
                     scales: "Optional[list[Optional[SynthesisConfig]]]"
                     = None):
        """Batch ids → audio in ONE device round trip (estimate + retry).

        The frame budget comes from the adaptive estimator rather than a
        device sync: the whole batch is a single dispatch whose result
        transfer also carries the true per-row frame requirements.  If the
        estimate was too small (rare; the estimator tracks an upper bound)
        the batch reruns once with a bucket that is known to fit.
        """
        return self._finish_batch(
            self._enqueue_batch(ids_list, sc, speakers=speakers,
                                scales=scales))

    def _enqueue_batch(self, ids_list: list[list[int]], sc: SynthesisConfig,
                       speakers: Optional[list[Optional[int]]] = None,
                       scales: "Optional[list[Optional[SynthesisConfig]]]"
                       = None) -> dict:
        """Asynchronously dispatch one batch; returns a ticket for
        :meth:`_finish_batch`.  Split from the fetch so callers can keep
        several dispatches in flight (``speak_batch`` pipelines them)."""
        n_real = len(ids_list)
        ids, lens, b, t = self._pad_batch(ids_list)
        sid = self._sid_array(sc, b, speakers)
        nw, ls, ns, ls_host = self._scale_arrays(sc, b, scales)
        weighted_ids = float(max(
            len(row) * max(ls_host[i], 0.05)
            for i, row in enumerate(ids_list)))
        # one key for both dispatches: the overflow retry must reproduce the
        # exact duration draw it measured, or the bigger bucket could clip
        # a fresh, longer draw
        rng = self._next_rng()
        args = [self.params, ids, lens, rng, nw, ls, ns]
        if sid is not None:
            args.append(sid)
        f = self._estimate_frame_bucket(weighted_ids)
        with self._jit_lock:
            cached = (b, t, f) in self._full_cache
        # dispatch attribution for whoever opened the channel (the batch
        # scheduler, around speak_batch): the padded shape this batch
        # actually ran at, what the padding cost, and whether this shape
        # paid an XLA compile — the single biggest TTFB outlier cause.
        # Group-wise: one speak_batch may issue several device programs,
        # and a cold group must never be shadowed by a later cached one
        # non-default length scales change the frame estimate, so their
        # shapes sit OUTSIDE the warmup lattice's coverage promise —
        # flagged here so the scope's cold-compile containment doesn't
        # report a legitimate scaled request as a coverage regression
        scaled = any(abs(l - sc.length_scale) > 1e-9
                     for l in ls_host[:n_real])
        tracing.annotate_dispatch_group(
            batch_bucket=b, text_bucket=t, frame_bucket=f, rows=n_real,
            padding_rows=b - n_real,
            padding_ratio=round((b - n_real) / b, 3),
            compile="cached" if cached else "cold",
            **({"scaled": True} if scaled else {}))
        out = self._full_fn(b, t, f)(*args)  # async dispatch
        self._prefetch_to_host(out)
        return {"out": out, "args": args, "b": b, "t": t, "f": f,
                "n_real": n_real, "weighted_ids": weighted_ids,
                "t_enqueue": time.perf_counter()}

    @staticmethod
    def _prefetch_to_host(out) -> None:
        """Start the device→host copy of a dispatch's outputs immediately.

        The copy engine runs the D2H transfer as soon as the program
        finishes, overlapping it with whatever computes next; the later
        ``device_get`` then finds the host copy already materialized
        (measured: ~250 ms blocking fetch of a 2 MB result over a remote
        PJRT link drops to ~0.2 ms).  Purely an optimization — any
        failure falls back to the blocking fetch path.
        """
        for a in (out if isinstance(out, (tuple, list)) else (out,)):
            try:
                a.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass

    def _finish_batch(self, ticket: dict):
        """Fetch a ticket's result; on frame-budget overflow re-dispatch
        once with a bucket that is known to fit (same RNG key → identical
        duration draw → identical audio)."""
        # one batched fetch: per-array round trips through a remote
        # PJRT link cost ~70 ms each; device_get coalesces them
        wav_i16, wav_lengths, peaks, frames_needed = jax.device_get(
            ticket["out"])
        n_real = ticket["n_real"]
        actual = int(frames_needed[:n_real].max())
        self._observe_frames(ticket["weighted_ids"], actual)
        if actual > ticket["f"]:  # overflow: audio was clipped; rerun
            f = bucket_for(actual, FRAME_BUCKETS)
            out = self._full_fn(ticket["b"], ticket["t"], f)(*ticket["args"])
            # no prefetch here: the blocking fetch on the next line leaves
            # nothing for an async D2H copy to overlap with
            wav_i16, wav_lengths, peaks, frames_needed = jax.device_get(out)
        wav_i16 = wav_i16[:n_real]
        peaks = np.maximum(peaks[:n_real, None], 0.01)
        # dequantize back to the model's original amplitudes
        wav = wav_i16.astype(np.float32) * (peaks / 32767.0)
        return wav, wav_lengths[:n_real]

    # ------------------------------------------------------------------
    # streaming (reference stream_synthesis, piper/src/lib.rs:652-668)
    # ------------------------------------------------------------------

    def stream_synthesis(self, phonemes: str, chunk_size: int,
                         chunk_padding: int,
                         deadline=None) -> Iterator[Audio]:
        """``deadline``: optional per-request
        :class:`~sonata_tpu.serving.deadlines.Deadline` — in iteration
        mode the resident stream carries it, so expiry mid-flight fails
        *this* stream at an iteration boundary without touching its
        batch peers."""
        sc = self.get_fallback_synthesis_config()
        with tracing.span("encode-ids"):
            ids = self._encode_phonemes(phonemes)
        info = self.audio_output_info()
        hop = self.hp.hop_length

        t_enc0 = time.perf_counter()
        # encode + acoustics ride the shared stage coalescer: N streams
        # starting within the wait window become ONE batched encode and
        # ONE batched acoustics dispatch (the reference gives each stream
        # its own blocking session, grpc/src/main.rs:381-409 — linear
        # degradation under load; here the device sees a batch)
        with tracing.span("encode-acoustics") as enc_sp:
            z_row, total_frames, f, sid0 = self._stream_stages.start(ids,
                                                                     sc)
            enc_sp.annotate(frame_bucket=f)
        total_frames = min(total_frames, f)
        enc_ms = (time.perf_counter() - t_enc0) * 1000.0

        # the encode landed: this stream's window decodes join the
        # device's running batch (iteration mode) or the wave coalescer
        # (dispatch mode); one engine resolved per stream, so a ladder
        # flip mid-stream cannot split a stream across engines
        decoder = self._stream_decoder
        join = getattr(decoder, "join", None)
        handle = join(deadline) if join is not None else None

        # window decodes are independent given z, so they pipeline through
        # the engine (and batch with other streams') while the consumer
        # drains chunk by chunk — but only a bounded look-ahead is in
        # flight: a stream abandoned early (gRPC client cancel drops the
        # generator) then wastes at most LOOKAHEAD window decodes and
        # batch slots instead of decoding its whole tail on-device.
        LOOKAHEAD = 3
        plans = list(plan_chunks(total_frames, chunk_size, chunk_padding))

        # fused decode epilogue (default): the crossfade taper and the
        # i16 quantize ride the decode's device program — the host only
        # dequantizes and slices, so the per-chunk epilogue leaves the
        # TTFB path and the D2H transfer halves
        fused = self.fused_epilogue != "off"

        def submit(plan):
            width = bucket_for(plan.width, FRAME_BUCKETS)
            start = min(plan.win_start, max(f - width, 0))
            shift = plan.win_start - start  # window moved left by pad
            lo = (shift + plan.trim_left) * hop
            hi = (shift + plan.width - plan.trim_right) * hop
            return (plan, start, width, lo, hi,
                    decoder.submit(z_row, start, width, sid0,
                                   stream=handle,
                                   epilogue=(lo, hi) if fused else None))

        try:
            submitted = [submit(p) for p in plans[:LOOKAHEAD]]
            next_i = len(submitted)
            while submitted:
                plan, start, width, lo, hi, fut = submitted.pop(0)
                t0 = time.perf_counter()
                with tracing.span("decode-window", width=width):
                    out = fut.result()
                if fused:
                    q, peak = out
                    # slice BEFORE dequantizing: the device zeroed
                    # everything outside [lo, hi), so the float work
                    # stays proportional to the emitted chunk
                    samples = AudioSamples(
                        decode_opts.dequantize_chunk(q[lo:hi], peak))
                    # taper already applied on device
                else:
                    samples = AudioSamples(out[lo:hi])
                    samples.crossfade(CROSSFADE_SAMPLES)  # taper (:838)
                ms = (time.perf_counter() - t0) * 1000.0 + enc_ms
                enc_ms = 0.0  # encoder cost attributed to the first chunk
                if next_i < len(plans):  # top up look-ahead before yield
                    submitted.append(submit(plans[next_i]))
                    next_i += 1
                yield Audio(samples, info, inference_ms=ms)
        finally:
            # stream end OR abandonment (gRPC cancel closes the
            # generator): retire from the running batch at the next
            # iteration boundary; pending look-ahead rows are cancelled
            if handle is not None:
                decoder.retire(handle)


# the generic queue-drain helper moved into the batching core with the
# rest of the gather/dispatch machinery; re-exported here because the
# coalescer drain contract is pinned against this module
_drain_pending_futures = drain_pending_futures


def _assemble_window_dispatch(v: "PiperVoice", key, payloads: list,
                              b: int):
    """Build one window-decode group's (fn, args) padded to ``b`` rows —
    the ONE place the (window, sid[, lo, hi]) payload layout is
    consumed, shared by both engines so the fused contract cannot
    desynchronize between them."""
    width, has_sid, fused = key
    pad = b - len(payloads)
    windows = jnp.stack([p[0] for p in payloads]
                        + [payloads[0][0]] * pad)
    args = [v.params, windows]
    if fused:
        args += [jnp.asarray([p[2] for p in payloads]
                             + [payloads[0][2]] * pad, jnp.int32),
                 jnp.asarray([p[3] for p in payloads]
                             + [payloads[0][3]] * pad, jnp.int32)]
    if has_sid:
        args.append(jnp.asarray(
            [p[1] for p in payloads] + [payloads[0][1]] * pad,
            dtype=jnp.int32))
    fn = (v._decode_windows_fused_fn(width, b, has_sid) if fused
          else v._decode_windows_batch_fn(width, b, has_sid))
    return fn, args


def _fetch_window_results(out, n: int, fused: bool) -> list:
    """The finisher-side twin: blocking fetch + per-row unpack.  Fused
    results are (i16 row, peak) pairs; plain results f32 rows."""
    if fused:
        q, peaks = jax.device_get(out)
        q, peaks = np.asarray(q), np.asarray(peaks)
        return [(q[i], float(peaks[i])) for i in range(n)]
    return list(np.asarray(jax.device_get(out))[:n])


class _StreamDecodeCoalescer:
    """Shared dispatcher for streaming window decodes (dispatch mode).

    The reference serves each realtime stream from its own blocking thread
    (``grpc/src/main.rs:381-409``), so N concurrent streams contend for
    the device with N independent decode calls per chunk wave.  Here every
    stream's window decode funnels through one queue; the batching core
    groups requests of equal window width (and same z frame-bucket shape)
    that arrive within ``max_wait_ms`` and this class issues ONE batched
    decode — under concurrent load the chunk cost approaches one dispatch
    per wave instead of one per stream, while a lone stream pays only the
    tiny wait window.

    Since the batching-core unification the queue/gather/drain machinery
    lives in :class:`~sonata_tpu.synth.batching.BatchingCore` (two-phase:
    the dispatcher thread enqueues device programs back-to-back while the
    finisher blocks on each async-prefetched result copy — a single
    thread doing both serialized every wave behind the previous wave's
    ~100 ms host-link fetch); this class keeps only the decode policy.
    """

    def __init__(self, voice: "PiperVoice", *, max_batch: int = 8,
                 max_wait_ms: float = 2.0):
        import weakref

        # weak back-reference: the voice owns the coalescer; a strong ref
        # here would pin the voice (and its params) to this thread's frame
        # for process lifetime
        self._voice_ref = weakref.ref(voice)
        self._max_batch = max_batch
        self._max_wait = max_wait_ms / 1000.0
        self._reason = "stream-decode coalescer closed (voice unloaded)"
        self._core = BatchingCore(
            dispatch=self._dispatch, finish=self._finish,
            max_batch=max_batch, max_wait_s=self._max_wait,
            name="sonata_stream_decoder", keyed=True,
            alive=lambda: self._voice_ref() is not None,
            closed_reason=self._reason, poll_s=5.0)
        self.stats = self._core.stats

    # thread handles pinned by the close/teardown tests
    @property
    def _worker(self):
        return self._core._worker

    @property
    def _finisher(self):
        return self._core._finisher

    def close(self) -> None:
        """Stop both threads and fail any work still queued.

        The core joins the worker before draining so nothing is added to
        a queue after its drain; requests already dispatched to the
        device resolve normally via the finisher before it exits."""
        self._core.shutdown(join_timeout_s=10.0)

    def submit(self, z_row, start: int, width: int, sid: "Optional[int]",
               stream=None, epilogue=None):
        """Enqueue a window decode; returns a Future of the [width*hop]
        waveform — or, with ``epilogue=(lo, hi)`` (the fused-epilogue
        arm), of an ``(i16 samples, peak)`` pair already tapered on
        device.  ``z_row``: [F, C] device array.  ``stream`` is the
        iteration-mode join handle — ignored here (dispatch mode has no
        resident-stream state).

        The window is sliced out of ``z_row`` here, eagerly (a tiny
        on-device op), so everything behind the queue handles fixed
        [width, C] windows regardless of the utterance's frame bucket —
        see :meth:`PiperVoice._decode_windows_batch_fn`.  Fused and
        plain submissions carry distinct keys (different executables
        AND result types), so they never share a dispatch group."""
        window = jax.lax.dynamic_slice_in_dim(
            z_row, jnp.int32(start), width, axis=0)
        fused = epilogue is not None
        payload = ((window, sid, epilogue[0], epilogue[1]) if fused
                   else (window, sid))
        item = WorkItem(payload, key=(width, sid is not None, fused))
        if self._core.closed:
            try_set_exception(item.future, OperationError(self._reason))
            return item.future
        self._core.put(item)
        return item.future

    def decode(self, z_row, start: int, width: int,
               sid: "Optional[int]") -> np.ndarray:
        """Blocking variant of :meth:`submit`."""
        return self.submit(z_row, start, width, sid).result()

    def _dispatch(self, group: list):
        v = self._voice_ref()
        if v is None:
            raise OperationError("voice was garbage-collected")
        n = len(group)
        # any multi-window group pads to ONE canonical batch size: the
        # executable set is then exactly {b=1, b=max} — both prewarmed
        # — so concurrency can never hit a cold compile mid-request.
        # The padding rows' decode compute is cheap next to the
        # XLA-compile stall a graduated bucket ladder risks per rung.
        # (Iteration mode walks the graduated ladder instead — and warms
        # every rung through the lattice; see _IterationStreamDecoder.)
        b = self._max_batch if n > 1 else 1
        fused = group[0].key[2]
        fn, args = _assemble_window_dispatch(
            v, group[0].key, [item.payload for item in group], b)
        out = fn(*args)  # async dispatch
        PiperVoice._prefetch_to_host(out)
        self._core.bump("requests", n)
        self._core.bump("dispatches")
        # padding accounting, same keys as the iteration loop's stats —
        # the bench's iteration-vs-dispatch A/B compares these directly
        self._core.bump("rows", n)
        self._core.bump("padded_rows", b - n)
        return (out, fused)

    def _finish(self, group: list, ticket) -> None:
        out, fused = ticket
        results = _fetch_window_results(out, len(group), fused)
        for item, res in zip(group, results):
            try_set_result(item.future, res)


class _IterationStreamDecoder:
    """Iteration-mode window decoder (``SONATA_BATCH_MODE=iteration``).

    Same ``submit`` surface as :class:`_StreamDecodeCoalescer`, but the
    engine underneath is the persistent
    :class:`~sonata_tpu.synth.batching.IterationLoop`: a stream *joins*
    the device's running batch once its encode lands, each of its window
    decodes rides an iteration alongside every other resident stream's
    rows, and the stream *retires* at an iteration boundary when it ends.
    No wave-gather wait window, and the batch axis steps the graduated
    bucket ladder (1, 2, 4, 8) — lattice-warmed, so occupancy-sized
    dispatches stay recompile-free where dispatch mode overpads every
    multi-stream wave to the canonical max.
    """

    def __init__(self, voice: "PiperVoice", *, max_batch: int = 8):
        import weakref

        self._voice_ref = weakref.ref(voice)
        self._max_batch = max_batch
        self._max_wait = 0.0  # no gather window: joins happen at
        # iteration boundaries, not inside a wait loop
        attrs = {}
        device = getattr(voice, "device", None)
        if device is not None:
            attrs["device"] = str(device)
        # two-phase: _dispatch enqueues the device program (async D2H
        # prefetch started), _finish blocks on the result — with
        # SONATA_ITER_PIPELINE (default on) the loop's finisher thread
        # fetches iteration k while the worker dispatches k+1
        self._loop = IterationLoop(self._dispatch, max_batch=max_batch,
                                   name="sonata_iter_decode", attrs=attrs,
                                   finish=self._finish)
        self.stats = self._loop.stats

    # -- stream lifecycle (stream_synthesis drives this) -----------------
    def join(self, deadline=None):
        return self._loop.join(deadline)

    def retire(self, handle) -> None:
        self._loop.retire(handle)

    def start_draining(self) -> None:
        self._loop.start_draining()

    @property
    def resident_streams(self) -> int:
        return self._loop.resident_streams

    def submit(self, z_row, start: int, width: int, sid: "Optional[int]",
               stream=None, epilogue=None):
        """Same eager-slice contract as the dispatch-mode coalescer
        (incl. the fused-epilogue ``epilogue=(lo, hi)`` arm).  Without a
        ``stream`` handle (direct callers, tools) the row rides as a
        one-iteration stream that retires when its future resolves."""
        window = jax.lax.dynamic_slice_in_dim(
            z_row, jnp.int32(start), width, axis=0)
        fused = epilogue is not None
        payload = ((window, sid, epilogue[0], epilogue[1]) if fused
                   else (window, sid))
        key = (width, sid is not None, fused)
        if stream is not None:
            return self._loop.submit(stream, key, payload)
        try:
            handle = self._loop.join()
        except OperationError as e:
            # closed/draining: fail the future instead of raising — the
            # same fail-fast contract as the dispatch-mode coalescer
            from concurrent.futures import Future

            fut: Future = Future()
            fut.set_exception(e)
            return fut
        fut = self._loop.submit(handle, key, payload)
        fut.add_done_callback(lambda _f: self._loop.retire(handle))
        return fut

    def decode(self, z_row, start: int, width: int,
               sid: "Optional[int]") -> np.ndarray:
        """Blocking variant of :meth:`submit`."""
        return self.submit(z_row, start, width, sid).result()

    def close(self) -> None:
        self._loop.close()

    # -- one iteration's device call (two-phase) ---------------------------
    def _dispatch(self, key, payloads, b: int):
        """DISPATCH phase: enqueue the iteration's device program and
        start the async D2H copy, without blocking on the result — the
        loop's finisher (``_finish``) fetches while the next iteration
        dispatches (``SONATA_ITER_PIPELINE``)."""
        v = self._voice_ref()
        if v is None:
            raise OperationError("voice was garbage-collected")
        width, has_sid, fused = key
        n = len(payloads)
        cache_key = v._wdec_cache_key(width, b, has_sid, fused)
        with v._jit_lock:
            cached = cache_key in v._dec_cache
        fn, args = _assemble_window_dispatch(v, key, payloads, b)
        out = fn(*args)  # async dispatch
        PiperVoice._prefetch_to_host(out)
        attrs = {"frame_bucket": width, "text_bucket": 0,
                 "compile": "cached" if cached else "cold"}
        voice_label = getattr(v, "scope_voice", None)
        if voice_label is not None:
            attrs["voice"] = voice_label
        return (out, n, fused), attrs

    @staticmethod
    def _finish(ticket):
        """FINISH phase: the blocking fetch — the only host sync on the
        iteration path, and it runs on the finisher thread so iteration
        k+1's dispatch overlaps it."""
        out, n, fused = ticket
        return _fetch_window_results(out, n, fused)


class _StreamStageCoalescer:
    """Shared dispatcher for streaming encode+acoustics stages.

    The window-decode coalescer (above) removed the per-chunk serialization
    across concurrent streams, but every stream still paid its own serial
    encode and acoustics dispatches at start — at 8 concurrent streams
    those per-stream stages dominated TTFB.  Here stream *starts* that
    arrive within ``max_wait_ms`` and share a text bucket become one
    batched encode and one batched acoustics dispatch; per-row synthesis
    scales and speaker ids ride the same row-wise arrays the batch path
    uses, so streams with different configs still share a dispatch.

    Pipeline shape mirrors the decode coalescer (and lives in the same
    :class:`~sonata_tpu.synth.batching.BatchingCore`): a dispatcher
    thread groups and enqueues device programs; a finisher thread blocks
    on each group's (async-prefetched) frame counts, handles the rare
    frame-budget retry, and resolves per-stream futures with their z row.
    """

    def __init__(self, voice: "PiperVoice", *, max_batch: int = 8,
                 max_wait_ms: float = 8.0):
        # max_wait is 4x the decode coalescer's: the stage runs once per
        # stream (vs once per chunk), so a slightly longer gather window
        # costs little TTFB but catches burst arrivals that thread
        # scheduling spreads over a few milliseconds
        import weakref

        self._voice_ref = weakref.ref(voice)
        self._max_batch = max_batch
        self._max_wait = max_wait_ms / 1000.0
        self._reason = "stream-stage coalescer closed (voice unloaded)"
        self._core = BatchingCore(
            dispatch=self._dispatch, finish=self._finish,
            max_batch=max_batch, max_wait_s=self._max_wait,
            name="sonata_stream_stages", keyed=True,
            alive=lambda: self._voice_ref() is not None,
            closed_reason=self._reason, poll_s=5.0)
        self.stats = self._core.stats

    @property
    def _worker(self):
        return self._core._worker

    @property
    def _finisher(self):
        return self._core._finisher

    def close(self) -> None:
        """Stop both threads and fail any work still queued (see
        :meth:`_StreamDecodeCoalescer.close`)."""
        self._core.shutdown(join_timeout_s=10.0)

    def start(self, ids: list, sc: SynthesisConfig):
        """Blocking: run encode+acoustics for one stream (possibly batched
        with others).  Returns ``(z_row, total_frames, f, sid0)`` where
        ``z_row`` is the [f, C] on-device latent, ``total_frames`` the true
        frame count, ``f`` the allocated frame bucket, and ``sid0`` the
        row's speaker id (None on single-speaker voices)."""
        if self._core.closed:
            raise OperationError(self._reason)
        item = WorkItem((ids, sc),
                        key=(bucket_for(len(ids), TEXT_BUCKETS),))
        self._core.put(item)
        return item.future.result()

    def _dispatch(self, group: list):
        v = self._voice_ref()
        if v is None:
            raise OperationError("voice was garbage-collected")
        ids_list = [item.payload[0] for item in group]
        scs = [item.payload[1] for item in group]
        # same canonical-batch rule as the decode coalescer: any
        # multi-stream group pads to max_batch rows, so only the
        # (b=1, b=max) encode/acoustics shapes exist and prewarm
        # covers them completely
        if len(group) > 1:
            pad_rows = self._max_batch - len(group)
            ids_list = ids_list + [[0]] * pad_rows
            scs = scs + [scs[0]] * pad_rows
        ids, lens, b, t = v._pad_batch(ids_list)
        speakers = None
        if v.multi_speaker:
            speakers = [sc.speaker[1] if sc.speaker else 0 for sc in scs]
        sid = v._sid_array(scs[0], b, speakers)
        nw, ls, ns, ls_host = v._scale_arrays(scs[0], b, scales=scs)
        weighted = max(len(row) * max(ls_host[i], 0.05)
                       for i, row in enumerate(ids_list))
        f = v._estimate_frame_bucket(weighted)
        # one split key per dispatch, like the fused batch path — a
        # frame-budget retry reuses it for identical audio
        rng_enc, rng_aco = jax.random.split(v._next_rng())
        enc_args = [v.params, ids, lens, rng_enc, nw, ls]
        if sid is not None:
            enc_args.append(sid)
        m_p, logs_p, w_ceil, x_mask = v._encode_fn(b, t)(*enc_args)
        # per-row frame counts: prefetched so the finisher's fetch
        # rides behind the acoustics dispatch
        frames_vec = jnp.sum(w_ceil.reshape(b, -1), axis=1)
        try:
            frames_vec.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass

        def run_acoustics(bucket: int):
            args = [v.params, m_p, logs_p, w_ceil, x_mask, rng_aco, ns]
            if sid is not None:
                args.append(sid)
            return v._acoustics_fn(b, t, bucket)(*args)

        z, _y_lengths = run_acoustics(f)
        self._core.bump("requests", len(group))
        self._core.bump("dispatches")
        self._core.bump("rows", len(group))
        self._core.bump("padded_rows", b - len(group))
        return (z, frames_vec, f, weighted, speakers, run_acoustics)

    def _finish(self, group: list, ticket) -> None:
        z, frames_vec, f, weighted, speakers, run_acoustics = ticket
        v = self._voice_ref()
        frames = np.asarray(jax.device_get(frames_vec)).astype(int)
        actual = int(frames[:len(group)].max())
        if v is not None:
            v._observe_frames(weighted, actual)
        if actual > f and v is not None:  # clipped: redo, same rng
            f = bucket_for(actual, FRAME_BUCKETS)
            z, _ = run_acoustics(f)
        for i, item in enumerate(group):
            sid0 = speakers[i] if speakers is not None else None
            try_set_result(item.future, (z[i], int(frames[i]), f, sid0))
