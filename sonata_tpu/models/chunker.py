"""Adaptive mel-frame chunk scheduling for streaming synthesis.

Reproduces the load-bearing behavior of the reference's
``AdaptiveMelChunker`` (``crates/sonata/models/piper/src/lib.rs:860-913``):

- chunk ``i`` (1-based) spans ``chunk_size * i`` frames, capped at
  ``MAX_CHUNK_SIZE = 1024`` (``:18-19,888``) — small first chunk for fast
  time-to-first-byte, growing chunks for throughput;
- consecutive chunks overlap by ``2 * chunk_padding`` frames, with the
  padding trimmed from the emitted audio (``:891-906``);
- a tail shorter than ``MIN_CHUNK_SIZE = 44`` frames merges into the final
  chunk (``:900``);
- a one-shot path when the utterance fits ``2*chunk + 2*padding`` frames
  (``:785,846-853``);
- frame→sample indexing is ``× hop`` (256 in Piper voices, ``:910``).

TPU addition: each window can be padded up to a power-of-two-ish bucket so
the jitted decoder compiles a bounded set of shapes (the reference's ORT
decoder takes any shape; XLA cannot).
"""

from __future__ import annotations

from dataclasses import dataclass

MAX_CHUNK_SIZE = 1024  # frames (piper/src/lib.rs:18)
MIN_CHUNK_SIZE = 44    # frames (piper/src/lib.rs:19)
CROSSFADE_SAMPLES = 42  # per-chunk edge taper (piper/src/lib.rs:838)


@dataclass(frozen=True)
class ChunkPlan:
    """One decoder dispatch: decode frames [win_start, win_end), then trim
    ``trim_left``/``trim_right`` frames' worth of samples from the edges."""

    win_start: int
    win_end: int
    trim_left: int
    trim_right: int

    @property
    def width(self) -> int:
        return self.win_end - self.win_start

    def sample_slice(self, hop: int) -> tuple[int, int]:
        """Slice into the decoded window's samples, post-trim."""
        return self.trim_left * hop, (self.win_end - self.win_start - self.trim_right) * hop


def plan_chunks(total_frames: int, chunk_size: int,
                chunk_padding: int) -> list[ChunkPlan]:
    """Compute the full chunk schedule for an utterance."""
    if total_frames <= 0:
        return []
    if total_frames <= 2 * chunk_size + 2 * chunk_padding:
        return [ChunkPlan(0, total_frames, 0, 0)]  # one-shot (:846-853)
    plans: list[ChunkPlan] = []
    start, step = 0, 1
    while start < total_frames:
        size = min(chunk_size * step, MAX_CHUNK_SIZE)
        end = min(start + size, total_frames)
        if total_frames - end < MIN_CHUNK_SIZE:
            end = total_frames  # merge short tail (:900)
        ws = max(start - chunk_padding, 0)
        we = min(end + chunk_padding, total_frames)
        plans.append(ChunkPlan(ws, we, start - ws, we - end))
        start = end
        step += 1
    return plans


