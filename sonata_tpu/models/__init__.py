"""Model implementations (analogue of ``crates/sonata/models``)."""

from pathlib import Path
from typing import Union

from .config import (
    ModelConfig,
    SynthesisConfig,
    VitsHyperParams,
    default_phoneme_id_map,
)
from .piper import PiperVoice


def from_config_path(config_path: Union[str, Path], **kwargs) -> PiperVoice:
    """Load a voice from a Piper JSON config (reference factory:
    ``crates/sonata/models/piper/src/lib.rs:88-110``)."""
    return PiperVoice.from_config_path(config_path, **kwargs)


__all__ = [
    "ModelConfig",
    "SynthesisConfig",
    "VitsHyperParams",
    "default_phoneme_id_map",
    "PiperVoice",
    "from_config_path",
]
