"""Piper-flavor VITS, implemented natively in JAX.

The reference executes this model as a black-box ONNX graph through
onnxruntime (``crates/sonata/models/piper/src/lib.rs:342-399`` single-graph;
``:537-574`` + ``:736-762`` encoder/decoder split).  Here the graph is
re-implemented as pure functions so XLA compiles it straight to TPU:

- ``encode_text``    — text encoder + stochastic duration predictor
                       → frame durations and phoneme-level priors.
- ``acoustics``      — length regulation (generate_path), prior sampling,
                       residual-coupling flow (reverse) → latent ``z``.
- ``decode``         — HiFi-GAN generator: ``z`` → waveform.
- ``infer``          — the composition, one jittable graph.

The encode/decode split mirrors the reference's streaming
``VitsStreamingModel`` contract (``EncoderOutputs{z, y_mask, g}`` →
decoder slices of ``z``, ``piper/src/lib.rs:671-762``), but the split point
is chosen for TPU: everything with data-dependent sizing (durations) lives
in ``encode_text``; ``acoustics``/``decode`` take static frame buckets so
each bucket compiles once and is reused.

RNG is explicit: the reference's ``scales``-driven noise is generated inside
the ONNX graph; here the caller passes a ``jax.random`` key so batched
synthesis draws independent noise per sentence (SURVEY §7 "RNG semantics").

All tensors are ``[batch, time, channels]``; masks ``[B, T, 1]``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .config import VitsHyperParams
from . import modules as m

Params = dict


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_text_encoder(rng, hp: VitsHyperParams, n_vocab: int) -> Params:
    r_emb, r_enc, r_proj = jax.random.split(rng, 3)
    return {
        "emb": jax.random.normal(r_emb, (n_vocab, hp.hidden_channels))
        * (hp.hidden_channels ** -0.5),
        "encoder": m.init_transformer(
            r_enc, channels=hp.hidden_channels,
            filter_channels=hp.filter_channels, n_heads=hp.n_heads,
            n_layers=hp.n_layers, kernel=hp.kernel_size, window=hp.attn_window,
        ),
        "proj": m._conv_init(r_proj, 1, hp.hidden_channels, 2 * hp.inter_channels),
    }


def init_duration_predictor(rng, hp: VitsHyperParams, gin: int) -> Params:
    rngs = jax.random.split(rng, 8)
    filt = hp.dp_filter_channels
    p: Params = {
        "pre": m._conv_init(rngs[0], 1, hp.hidden_channels, filt),
        "convs": m.init_dds_conv(rngs[1], channels=filt,
                                 kernel=hp.dp_kernel_size, n_layers=3),
        "proj": m._conv_init(rngs[2], 1, filt, filt),
        "affine": {"m": jnp.zeros((2,)), "logs": jnp.zeros((2,))},
        "flows": [],
    }
    if gin:
        p["cond"] = m._conv_init(rngs[3], 1, gin, filt)
    for i in range(hp.dp_n_flows):
        r = jax.random.fold_in(rngs[4], i)
        r1, r2, r3 = jax.random.split(r, 3)
        n_out = 3 * hp.dp_num_bins - 1
        p["flows"].append({
            "pre": m._conv_init(r1, 1, 1, filt),
            "convs": m.init_dds_conv(r2, channels=filt,
                                     kernel=hp.dp_kernel_size, n_layers=3),
            "proj": {"w": jnp.zeros((1, filt, n_out)),
                     "b": jnp.zeros((n_out,))},  # zero-init → identity start
        })
    return p


def init_flow(rng, hp: VitsHyperParams, gin: int) -> Params:
    half = hp.inter_channels // 2
    layers = []
    for i in range(hp.flow_n_layers):
        r = jax.random.fold_in(rng, i)
        r1, r2, r3 = jax.random.split(r, 3)
        layers.append({
            "pre": m._conv_init(r1, 1, half, hp.hidden_channels),
            "wn": m.init_wn(r2, hidden=hp.hidden_channels,
                            kernel=hp.flow_kernel_size, dilation_rate=1,
                            n_layers=hp.flow_wn_layers, gin_channels=gin),
            "post": {"w": jnp.zeros((1, hp.hidden_channels, half)),
                     "b": jnp.zeros((half,))},  # zero-init (identity start)
        })
    return {"layers": layers}


def init_generator(rng, hp: VitsHyperParams, gin: int) -> Params:
    rngs = jax.random.split(rng, 4)
    ch0 = hp.upsample_initial_channel
    p: Params = {
        "conv_pre": m._conv_init(rngs[0], 7, hp.inter_channels, ch0),
        "ups": [],
        "resblocks": [],
        "conv_post": m._conv_init(rngs[1], 7, ch0 // (2 ** len(hp.upsample_rates)), 1),
    }
    if gin:
        p["cond"] = m._conv_init(rngs[2], 1, gin, ch0)
    for i, (r_up, k_up) in enumerate(zip(hp.upsample_rates, hp.upsample_kernel_sizes)):
        r = jax.random.fold_in(rngs[3], i)
        c_in, c_out = ch0 // (2 ** i), ch0 // (2 ** (i + 1))
        p["ups"].append(m._conv_init(r, k_up, c_in, c_out))
        for j, (k_res, dils) in enumerate(
            zip(hp.resblock_kernel_sizes, hp.resblock_dilation_sizes)
        ):
            rr = jax.random.fold_in(r, 100 + j)
            block = {"convs1": [], "convs2": []}
            for di, d in enumerate(dils):
                ra = jax.random.fold_in(rr, di)
                ra1, ra2 = jax.random.split(ra)
                block["convs1"].append(m._conv_init(ra1, k_res, c_out, c_out))
                block["convs2"].append(m._conv_init(ra2, k_res, c_out, c_out))
            p["resblocks"].append(block)
    return p


def init_vits(rng, hp: VitsHyperParams, *, n_vocab: int,
              n_speakers: int = 1) -> Params:
    rngs = jax.random.split(rng, 5)
    gin = hp.gin_channels if n_speakers > 1 else 0
    p: Params = {
        "enc_p": init_text_encoder(rngs[0], hp, n_vocab),
        "dp": init_duration_predictor(rngs[1], hp, gin),
        "flow": init_flow(rngs[2], hp, gin),
        "dec": init_generator(rngs[3], hp, gin),
    }
    if n_speakers > 1:
        p["emb_g"] = jax.random.normal(rngs[4], (n_speakers, hp.gin_channels)) * 0.02
    return p


# ---------------------------------------------------------------------------
# stage 1: text encoder + stochastic duration predictor
# ---------------------------------------------------------------------------

def sequence_mask(lengths, max_len: int):
    """[B] lengths → [B, max_len, 1] float mask."""
    idx = jnp.arange(max_len)[None, :]
    return (idx < lengths[:, None]).astype(jnp.float32)[..., None]


def per_row_normal(rng, shape):
    """Standard-normal draws with **per-row** keys: row ``i`` of the
    ``[B, ...]`` output is drawn from ``fold_in(rng, i)`` over the
    per-row shape alone.

    A single batch-shaped draw makes every row's values a function of the
    whole batch shape — so padding the batch (mesh data-axis rounding, a
    coalesced group's dummy rows) silently changes every *real* row's
    noise, and sharded vs unsharded dispatches of the same sentence
    diverge (the 6 former test_parallel xfails).  Per-row keys make a
    row's draw depend only on (key, row index, row shape): batch
    neighbors and padding rows cannot perturb it, which is also the
    correctness contract continuous batching needs — a request's audio
    must not depend on whatever shared its dispatch.  Row shapes stay
    bucket-stable because both the text and frame axes are bucketed
    identically with or without a mesh.
    """
    keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
        jnp.arange(shape[0]))
    return jax.vmap(lambda k: jax.random.normal(k, shape[1:]))(keys)


def text_encoder(p: Params, hp: VitsHyperParams, ids, x_mask, mesh=None):
    x = p["emb"][ids] * math.sqrt(hp.hidden_channels)  # [B, T, H]
    seq = 0 if mesh is None else mesh.shape.get("seq", 1)
    if mesh is not None and seq > 1 and x.shape[1] % seq == 0:
        # sequence parallelism: ring attention + halo convs over the
        # mesh's seq axis (long inputs shard along time)
        x = m.transformer_seq_parallel(x, x_mask, p["encoder"],
                                       n_heads=hp.n_heads,
                                       window=hp.attn_window, mesh=mesh)
    else:
        x = m.transformer(x, x_mask, p["encoder"], n_heads=hp.n_heads,
                          window=hp.attn_window)
    stats = m.conv1d(x, p["proj"]) * x_mask
    m_p, logs_p = jnp.split(stats, 2, axis=-1)
    return x, m_p, logs_p


def duration_predictor_reverse(p: Params, hp: VitsHyperParams, x, x_mask,
                               rng, noise_w, g=None):
    """Stochastic duration predictor, inference (reverse-flow) path → logw.

    Flow order replicates VITS inference exactly, including the quirk that
    the first ConvFlow is skipped at inference time (the exported Piper
    graphs bake this in, so weight-parity requires it).
    """
    h = m.conv1d(x, p["pre"])
    if g is not None and "cond" in p:
        h = h + m.conv1d(g, p["cond"])
    h = m.dds_conv(h, x_mask, p["convs"], kernel=hp.dp_kernel_size)
    h = m.conv1d(h, p["proj"]) * x_mask

    b, t, _ = x.shape
    # noise_w may be a scalar or a per-row [B] vector (coalesced batches
    # carry per-request scales)
    noise_w = jnp.reshape(jnp.asarray(noise_w, jnp.float32), (-1, 1, 1))
    z = per_row_normal(rng, (b, t, 2)) * noise_w * x_mask

    # reversed flow stack: Flip/ConvFlow pairs (skipping ConvFlow #0), then
    # the elementwise affine
    for i in range(hp.dp_n_flows - 1, 0, -1):
        z = z[..., ::-1]  # Flip
        z = _conv_flow_reverse(p["flows"][i], hp, z, x_mask, h)
    z = z[..., ::-1]  # Flip preceding the skipped ConvFlow #0
    # ElementwiseAffine reverse: x = (z - m) * exp(-logs)
    aff = p["affine"]
    z = (z - aff["m"]) * jnp.exp(-aff["logs"]) * x_mask
    logw = z[..., 0:1]
    return logw


def _conv_flow_reverse(pf: Params, hp: VitsHyperParams, z, mask, g):
    z0, z1 = z[..., 0:1], z[..., 1:2]
    h = m.conv1d(z0, pf["pre"])
    h = m.dds_conv(h, mask, pf["convs"], kernel=hp.dp_kernel_size, g=g)
    h = m.conv1d(h, pf["proj"]) * mask  # [B, T, 3*bins-1]
    nb = hp.dp_num_bins
    filt = hp.dp_filter_channels
    uw = h[..., :nb] / math.sqrt(filt)
    uh = h[..., nb:2 * nb] / math.sqrt(filt)
    ud = h[..., 2 * nb:]
    x1, _ = m.rational_quadratic_spline_inverse(
        z1[..., 0], uw, uh, ud, tail_bound=hp.dp_tail_bound
    )
    return jnp.concatenate([z0, x1[..., None] * mask], axis=-1)


def encode_text(p: Params, hp: VitsHyperParams, ids, x_lengths, rng, *,
                noise_w: float, length_scale: float, sid=None, mesh=None):
    """ids [B, T] → (m_p, logs_p [B, T, C], durations w_ceil [B, T], g).

    Everything whose output size depends on data (durations) is computed
    here; downstream stages take a static frame budget.
    """
    x_mask = sequence_mask(x_lengths, ids.shape[1])
    g = None
    if sid is not None and "emb_g" in p:
        g = p["emb_g"][sid][:, None, :]  # [B, 1, gin]
    x, m_p, logs_p = text_encoder(p["enc_p"], hp, ids, x_mask, mesh=mesh)
    logw = duration_predictor_reverse(p["dp"], hp, x, x_mask, rng,
                                      noise_w, g=g)
    length_scale = jnp.reshape(jnp.asarray(length_scale, jnp.float32),
                               (-1, 1, 1))  # scalar or per-row [B]
    w = jnp.exp(logw) * x_mask * length_scale
    w_ceil = jnp.ceil(w)[..., 0]  # [B, T]
    return m_p, logs_p, w_ceil, x_mask, g


# ---------------------------------------------------------------------------
# stage 2: length regulation + prior + flow reverse
# ---------------------------------------------------------------------------

def generate_path(w_ceil, x_mask, max_frames: int):
    """Monotonic alignment path from durations.

    ``w_ceil: [B, T]`` → ``path: [B, T, F]`` with ``path[b, t, f] = 1`` iff
    frame ``f`` belongs to phoneme ``t``.  Pure broadcasting — no scatter,
    no dynamic shapes; the MXU eats the downstream einsum.

    The exclusive prefix sum is ``cum - w`` (exact: durations are small
    integers), NOT the textbook zero-pad-and-slice concatenate.  On a
    mesh whose seq axis shards the T dimension, XLA's SPMD partitioner
    miscompiles the slice+concat shift (observed on jax 0.4.37: path
    rows off by one frame vs the unsharded graph for identical
    ``w_ceil`` — the former test_parallel mesh-numeric failures), while
    the subtraction form partitions correctly under every sharding.
    """
    w = w_ceil * x_mask[..., 0]
    cum = jnp.cumsum(w, axis=1)  # [B, T]
    f = jnp.arange(max_frames)[None, None, :]
    upper = f < cum[..., None]
    lower = f >= (cum - w)[..., None]
    return (upper & lower).astype(jnp.float32)


def acoustics(p: Params, hp: VitsHyperParams, m_p, logs_p, w_ceil, x_mask,
              rng, *, noise_scale: float, max_frames: int, g=None,
              mesh=None):
    """Durations + priors → latent ``z`` [B, F, C] and frame mask."""
    y_lengths = jnp.clip(jnp.sum(w_ceil, axis=1), 1, max_frames).astype(jnp.int32)
    y_mask = sequence_mask(y_lengths, max_frames)  # [B, F, 1]
    path = generate_path(w_ceil, x_mask, max_frames)  # [B, T, F]
    m_p_f = jnp.einsum("btf,btc->bfc", path, m_p)
    logs_p_f = jnp.einsum("btf,btc->bfc", path, logs_p)
    noise = per_row_normal(rng, m_p_f.shape)
    noise_scale = jnp.reshape(jnp.asarray(noise_scale, jnp.float32),
                              (-1, 1, 1))  # scalar or per-row [B]
    z_p = m_p_f + noise * jnp.exp(logs_p_f) * noise_scale
    if _use_seq_parallel(mesh, max_frames, hp):
        from .seq_parallel import flow_reverse_sp

        z = flow_reverse_sp(p["flow"], hp, z_p, y_mask, mesh, g=g)
    else:
        z = flow_reverse(p["flow"], hp, z_p, y_mask, g=g)
    return z * y_mask, y_mask, y_lengths


def _use_seq_parallel(mesh, frames: int, hp: VitsHyperParams) -> bool:
    """Frame-domain ops shard over the seq axis when the mesh has one and
    the per-shard frame count leaves room for every conv halo (the halos
    are neighbor-only, so each stage's local length must cover its
    largest receptive-field reach — derived from hp, not hard-coded)."""
    if mesh is None:
        return False
    if mesh.shape.get("model", 1) > 1:
        # tensor parallelism owns the flow/decoder when the model axis is
        # active: the sp shard_maps take params with replicated in_specs,
        # which would force an all-gather of the model-sharded decoder
        # weights on every dispatch and then compute the full channel
        # range redundantly on each tp chip — worse than either axis
        # alone.  Ring attention (text domain) still rides the seq axis.
        return False
    seq = mesh.shape.get("seq", 1)
    if seq <= 1 or frames % seq:
        return False
    from .seq_parallel import min_local_frames

    return frames // seq >= min_local_frames(hp)


def flow_reverse(pf: Params, hp: VitsHyperParams, z, mask, g=None,
                 conv=None):
    half = hp.inter_channels // 2
    for layer in reversed(pf["layers"]):
        z = z[..., ::-1]  # Flip (reverse order: undo the flip first)
        z0, z1 = z[..., :half], z[..., half:]
        h = m.conv1d(z0, layer["pre"]) * mask
        h = m.wn(h, mask, layer["wn"], kernel=hp.flow_kernel_size,
                 dilation_rate=1, n_layers=hp.flow_wn_layers, g=g,
                 conv=conv)
        mean = m.conv1d(h, layer["post"]) * mask
        z1 = (z1 - mean) * mask  # mean-only coupling, reverse
        z = jnp.concatenate([z0, z1], axis=-1)
    return z


# ---------------------------------------------------------------------------
# stage 3: HiFi-GAN decoder
# ---------------------------------------------------------------------------

def decode(p: Params, hp: VitsHyperParams, z, g=None, mesh=None,
           compute_dtype=None):
    """Latent ``z`` [B, F, C] → waveform [B, F * hop].

    The FLOPs live here (upsampling convs); channels shrink as time grows,
    keeping every conv an MXU-friendly matmul over the channel dim.  With
    a seq-axis mesh the frames (and output samples) shard across chips
    (:mod:`.seq_parallel`).

    ``compute_dtype``: optional reduced-precision policy for the conv
    stack (``jnp.bfloat16`` keeps the MXU in its native mode — one
    hardware pass instead of three for float32).  Weights and activations
    are cast on entry; the output returns to float32 before ``tanh`` so
    the final waveform (and its downstream i16 quantization) stays
    full-precision at the last nonlinearity.
    """
    if _use_seq_parallel(mesh, z.shape[1], hp):
        from .seq_parallel import decode_sp

        return decode_sp(p, hp, z, mesh, g=g, compute_dtype=compute_dtype)
    return decode_with(p, hp, z, g=g, compute_dtype=compute_dtype)


def decode_with(p: Params, hp: VitsHyperParams, z, g=None, conv=None,
                tconv=None, compute_dtype=None):
    """:func:`decode` body with injectable conv primitives — the
    sequence-sharded path passes halo-exchange versions, so the model
    math exists exactly once."""
    conv = conv or m.conv1d
    tconv = tconv or (lambda x, p_, *, stride, padding:
                      m.conv_transpose1d(x, p_, stride=stride,
                                         padding=padding))
    from .decode_opts import dequantize_decoder

    # int8 weight-only arm (SONATA_DECODE_QUANT): quantized conv weights
    # rescale to f32 here, inside the device program — a plain f32 tree
    # passes through untouched
    pd = dequantize_decoder(p["dec"])
    if compute_dtype is not None:
        # on-device cast of the decoder weights: pure HBM traffic (~0.1 ms
        # for the full stack), repaid many times over by MXU-native convs
        pd = jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype), pd)
        z = z.astype(compute_dtype)
        if g is not None:
            g = g.astype(compute_dtype)
    x = conv(z, pd["conv_pre"])
    if g is not None and "cond" in pd:
        x = x + m.conv1d(g, pd["cond"])
    n_kernels = len(hp.resblock_kernel_sizes)
    for i, (r_up, k_up) in enumerate(zip(hp.upsample_rates, hp.upsample_kernel_sizes)):
        x = jax.nn.leaky_relu(x, m.LRELU_SLOPE)
        x = tconv(x, pd["ups"][i], stride=r_up,
                  padding=(k_up - r_up) // 2)
        xs = None
        for j in range(n_kernels):
            block = pd["resblocks"][i * n_kernels + j]
            y = _resblock1(block, x, hp.resblock_kernel_sizes[j],
                           hp.resblock_dilation_sizes[j], conv=conv)
            xs = y if xs is None else xs + y
        x = xs / n_kernels
    x = jax.nn.leaky_relu(x, m.LRELU_SLOPE)
    x = conv(x, pd["conv_post"])
    return jnp.tanh(x.astype(jnp.float32))[..., 0]  # [B, samples]


def _resblock1(block: Params, x, kernel: int, dilations, conv=None):
    conv = conv or m.conv1d
    for c1, c2, d in zip(block["convs1"], block["convs2"], dilations):
        y = jax.nn.leaky_relu(x, m.LRELU_SLOPE)
        y = conv(y, c1, dilation=d)
        y = jax.nn.leaky_relu(y, m.LRELU_SLOPE)
        y = conv(y, c2)
        x = x + y
    return x


# ---------------------------------------------------------------------------
# full graph
# ---------------------------------------------------------------------------

def infer(p: Params, hp: VitsHyperParams, ids, x_lengths, rng, *,
          noise_scale: float = 0.667, length_scale: float = 1.0,
          noise_w: float = 0.8, max_frames: int = 1024, sid=None):
    """Single-graph inference: ids → waveform.

    Matches the reference's single-ONNX contract — inputs
    ``(input [B,T], input_lengths [B], scales, sid?)``
    (``piper/src/lib.rs:345-368``) — with explicit RNG and a static frame
    budget ``max_frames`` (the dynamic-shape boundary the ONNX graph hides).

    Returns (wav [B, max_frames*hop], wav_lengths [B] in samples).
    """
    rng_dur, rng_noise = jax.random.split(rng)
    m_p, logs_p, w_ceil, x_mask, g = encode_text(
        p, hp, ids, x_lengths, rng_dur, noise_w=noise_w,
        length_scale=length_scale, sid=sid,
    )
    z, y_mask, y_lengths = acoustics(
        p, hp, m_p, logs_p, w_ceil, x_mask, rng_noise,
        noise_scale=noise_scale, max_frames=max_frames, g=g,
    )
    wav = decode(p, hp, z, g=g)
    return wav, y_lengths * hp.hop_length
