"""Import Piper ONNX voice files without an ONNX runtime or the ``onnx``
package.

The reference hands the whole file to onnxruntime
(``crates/sonata/models/piper/src/lib.rs:79-86``).  We only need the
*weights*: ONNX is protobuf, and protobuf's wire format is simple enough to
parse directly — varint-keyed fields, length-delimited submessages.  This
module implements a minimal wire reader, walks ``ModelProto.graph`` (field
7) → ``GraphProto.initializer`` (field 5), decodes each ``TensorProto``, and
maps the torch-style initializer names that ``torch.onnx.export`` preserves
onto our pytree via :func:`.import_torch.state_dict_to_params`.

Field numbers follow the public ONNX schema (onnx/onnx.proto):
``TensorProto``: dims=1, data_type=2, float_data=4, int64_data=7, name=8,
raw_data=9.  Data types: FLOAT=1, INT64=7, FLOAT16=10, DOUBLE=11.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Union

import numpy as np

from ..core import FailedToLoadResource
from ..utils.protowire import (
    WIRE_LEN as _WIRE_LEN,
    WIRE_VARINT as _WIRE_VARINT,
    WireError,
    iter_fields as _iter_fields,
    read_varint as _read_varint,
)
from .config import VitsHyperParams


def iter_fields(buf):
    """protowire field iterator with errors mapped to resource failures."""
    try:
        yield from _iter_fields(buf)
    except WireError as e:
        raise FailedToLoadResource(f"malformed protobuf: {e}") from e


_DTYPE = {1: np.float32, 7: np.int64, 10: np.float16, 11: np.float64,
          6: np.int32, 9: np.bool_}


def _varints(value) -> list[int]:
    """Decode a packed-varint payload, mapping wire errors to load errors."""
    out: list[int] = []
    pos = 0
    mv = memoryview(value)
    try:
        while pos < len(mv):
            v, pos = _read_varint(mv, pos)
            out.append(v)
    except WireError as e:
        raise FailedToLoadResource(f"malformed packed varints: {e}") from e
    return out


def _decode_tensor(buf) -> tuple[str, np.ndarray]:
    dims: list[int] = []
    data_type = 1
    name = ""
    raw = None  # memoryview into the file buffer — zero-copy until np
    float_data: list[float] = []
    int64_data: list[int] = []
    for field, wire, value in iter_fields(buf):
        if field == 1:  # dims
            if wire == _WIRE_VARINT:
                dims.append(int(value))
            else:  # packed
                dims.extend(_varints(value))
        elif field == 2 and wire == _WIRE_VARINT:
            data_type = int(value)
        elif field == 8:
            name = bytes(value).decode("utf-8", errors="replace")
        elif field == 9:
            raw = value
        elif field == 4:  # float_data (packed or repeated)
            if wire == _WIRE_LEN:
                float_data.extend(
                    struct.unpack(f"<{len(value) // 4}f", value))
            else:
                float_data.append(struct.unpack("<f", value)[0])
        elif field == 7:  # int64_data
            if wire == _WIRE_LEN:
                int64_data.extend(_varints(value))
            else:
                int64_data.append(int(value))
    dtype = _DTYPE.get(data_type)
    if dtype is None:
        raise FailedToLoadResource(
            f"initializer {name!r}: unsupported ONNX data type {data_type}")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype).copy()
    elif float_data:
        arr = np.asarray(float_data, dtype=np.float32)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=np.int64)
    else:
        arr = np.zeros(0, dtype=dtype)
    if dims:
        arr = arr.reshape(dims)
    return name, arr


def read_onnx_initializers(path: Union[str, Path]) -> dict[str, np.ndarray]:
    """Extract ``{initializer name: ndarray}`` from an ONNX file."""
    data = Path(path).read_bytes()
    out: dict[str, np.ndarray] = {}
    for field, wire, value in iter_fields(memoryview(data)):
        if field == 7 and wire == _WIRE_LEN:  # ModelProto.graph
            for gfield, gwire, gvalue in iter_fields(value):
                if gfield == 5 and gwire == _WIRE_LEN:  # initializer
                    name, arr = _decode_tensor(gvalue)
                    out[name] = arr
                elif gfield == 1 and gwire == _WIRE_LEN:
                    # nodes may carry Constant-op tensors; skip (weights for
                    # VITS live in initializers)
                    continue
    if not out:
        raise FailedToLoadResource(
            f"{path}: no initializers found (not an ONNX model?)")
    return out


def import_onnx_weights(path: Union[str, Path], hp: VitsHyperParams, *,
                        n_vocab: int, n_speakers: int = 1) -> dict:
    """ONNX initializers → native param pytree.

    ``torch.onnx.export`` keeps parameter names for initializers, so the
    state-dict mapper applies directly.  Weight-norm is usually already
    fused in exports (piper removes it); if ``weight_g/v`` pairs survive,
    the mapper fuses them.
    """
    from .import_torch import state_dict_to_params, strip_prefix

    sd = read_onnx_initializers(path)
    sd = {k: v.astype(np.float32) if v.dtype in (np.float16, np.float64)
          else v for k, v in sd.items()}
    return state_dict_to_params(strip_prefix(sd), hp, n_vocab=n_vocab,
                                n_speakers=n_speakers)
