"""Import Piper ONNX voice files without an ONNX runtime or the ``onnx``
package.

The reference hands the whole file to onnxruntime
(``crates/sonata/models/piper/src/lib.rs:79-86``).  We only need the
*weights*: ONNX is protobuf, and protobuf's wire format is simple enough to
parse directly — varint-keyed fields, length-delimited submessages.  This
module implements a minimal wire reader, walks ``ModelProto.graph`` (field
7) → ``GraphProto.initializer`` (field 5), decodes each ``TensorProto``, and
maps the torch-style initializer names that ``torch.onnx.export`` preserves
onto our pytree via :func:`.import_torch.state_dict_to_params`.

Field numbers follow the public ONNX schema (onnx/onnx.proto):
``TensorProto``: dims=1, data_type=2, float_data=4, int64_data=7, name=8,
raw_data=9.  Data types: FLOAT=1, INT64=7, FLOAT16=10, DOUBLE=11.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Iterator, Union

import numpy as np

from ..core import FailedToLoadResource
from .config import VitsHyperParams

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_LEN = 2
_WIRE_32BIT = 5


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise FailedToLoadResource("truncated protobuf varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise FailedToLoadResource("malformed protobuf varint")


def iter_fields(buf: memoryview) -> Iterator[tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a protobuf message."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == _WIRE_VARINT:
            value, pos = _read_varint(buf, pos)
        elif wire == _WIRE_64BIT:
            value = buf[pos:pos + 8]
            pos += 8
        elif wire == _WIRE_LEN:
            n, pos = _read_varint(buf, pos)
            value = buf[pos:pos + n]
            pos += n
        elif wire == _WIRE_32BIT:
            value = buf[pos:pos + 4]
            pos += 4
        else:
            raise FailedToLoadResource(f"unsupported protobuf wire type {wire}")
        yield field, wire, value


_DTYPE = {1: np.float32, 7: np.int64, 10: np.float16, 11: np.float64,
          6: np.int32, 9: np.bool_}


def _decode_tensor(buf: memoryview) -> tuple[str, np.ndarray]:
    dims: list[int] = []
    data_type = 1
    name = ""
    raw = None
    float_data: list[float] = []
    int64_data: list[int] = []
    for field, wire, value in iter_fields(buf):
        if field == 1:  # dims
            if wire == _WIRE_VARINT:
                dims.append(int(value))
            else:  # packed
                pos = 0
                mv = memoryview(value)
                while pos < len(mv):
                    v, pos = _read_varint(mv, pos)
                    dims.append(v)
        elif field == 2 and wire == _WIRE_VARINT:
            data_type = int(value)
        elif field == 8:
            name = bytes(value).decode("utf-8", errors="replace")
        elif field == 9:
            raw = bytes(value)
        elif field == 4:  # float_data (packed or repeated)
            if wire == _WIRE_LEN:
                float_data.extend(
                    struct.unpack(f"<{len(value) // 4}f", bytes(value)))
            else:
                float_data.append(struct.unpack("<f", bytes(value))[0])
        elif field == 7:  # int64_data
            if wire == _WIRE_LEN:
                pos = 0
                mv = memoryview(value)
                while pos < len(mv):
                    v, pos = _read_varint(mv, pos)
                    int64_data.append(v)
            else:
                int64_data.append(int(value))
    dtype = _DTYPE.get(data_type)
    if dtype is None:
        raise FailedToLoadResource(
            f"initializer {name!r}: unsupported ONNX data type {data_type}")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype).copy()
    elif float_data:
        arr = np.asarray(float_data, dtype=np.float32)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=np.int64)
    else:
        arr = np.zeros(0, dtype=dtype)
    if dims:
        arr = arr.reshape(dims)
    return name, arr


def read_onnx_initializers(path: Union[str, Path]) -> dict[str, np.ndarray]:
    """Extract ``{initializer name: ndarray}`` from an ONNX file."""
    data = Path(path).read_bytes()
    out: dict[str, np.ndarray] = {}
    for field, wire, value in iter_fields(memoryview(data)):
        if field == 7 and wire == _WIRE_LEN:  # ModelProto.graph
            for gfield, gwire, gvalue in iter_fields(value):
                if gfield == 5 and gwire == _WIRE_LEN:  # initializer
                    name, arr = _decode_tensor(gvalue)
                    out[name] = arr
                elif gfield == 1 and gwire == _WIRE_LEN:
                    # nodes may carry Constant-op tensors; skip (weights for
                    # VITS live in initializers)
                    continue
    if not out:
        raise FailedToLoadResource(
            f"{path}: no initializers found (not an ONNX model?)")
    return out


def import_onnx_weights(path: Union[str, Path], hp: VitsHyperParams, *,
                        n_vocab: int, n_speakers: int = 1) -> dict:
    """ONNX initializers → native param pytree.

    ``torch.onnx.export`` keeps parameter names for initializers, so the
    state-dict mapper applies directly.  Weight-norm is usually already
    fused in exports (piper removes it); if ``weight_g/v`` pairs survive,
    the mapper fuses them.
    """
    from .import_torch import state_dict_to_params, strip_prefix

    sd = read_onnx_initializers(path)
    sd = {k: v.astype(np.float32) if v.dtype in (np.float16, np.float64)
          else v for k, v in sd.items()}
    return state_dict_to_params(strip_prefix(sd), hp, n_vocab=n_vocab,
                                n_speakers=n_speakers)
