"""Import Piper ONNX voice files without an ONNX runtime or the ``onnx``
package.

The reference hands the whole file to onnxruntime
(``crates/sonata/models/piper/src/lib.rs:79-86``).  We only need the
*weights*: ONNX is protobuf, and protobuf's wire format is simple enough to
parse directly — varint-keyed fields, length-delimited submessages.  This
module implements a minimal wire reader, walks ``ModelProto.graph`` (field
7) → ``GraphProto.initializer`` (field 5), decodes each ``TensorProto``, and
maps the torch-style initializer names that ``torch.onnx.export`` preserves
onto our pytree via :func:`.import_torch.state_dict_to_params`.

Field numbers follow the public ONNX schema (onnx/onnx.proto):
``TensorProto``: dims=1, data_type=2, float_data=4, int64_data=7, name=8,
raw_data=9.  Data types: FLOAT=1, INT64=7, FLOAT16=10, DOUBLE=11.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Union

import numpy as np

from ..core import FailedToLoadResource
from ..utils.protowire import (
    WIRE_32BIT as _WIRE_32BIT,
    WIRE_LEN as _WIRE_LEN,
    WIRE_VARINT as _WIRE_VARINT,
    WireError,
    iter_fields as _iter_fields,
    read_varint as _read_varint,
)
from .config import VitsHyperParams


def iter_fields(buf):
    """protowire field iterator with errors mapped to resource failures."""
    try:
        yield from _iter_fields(buf)
    except WireError as e:
        raise FailedToLoadResource(f"malformed protobuf: {e}") from e


_DTYPE = {1: np.float32, 7: np.int64, 10: np.float16, 11: np.float64,
          6: np.int32, 9: np.bool_}


def _varints(value) -> list[int]:
    """Decode a packed-varint payload, mapping wire errors to load errors."""
    out: list[int] = []
    pos = 0
    mv = memoryview(value)
    try:
        while pos < len(mv):
            v, pos = _read_varint(mv, pos)
            out.append(v)
    except WireError as e:
        raise FailedToLoadResource(f"malformed packed varints: {e}") from e
    return out


def _decode_tensor(buf) -> tuple[str, np.ndarray]:
    dims: list[int] = []
    data_type = 1
    name = ""
    raw = None  # memoryview into the file buffer — zero-copy until np
    float_data: list[float] = []
    int64_data: list[int] = []
    for field, wire, value in iter_fields(buf):
        if field == 1:  # dims
            if wire == _WIRE_VARINT:
                dims.append(int(value))
            else:  # packed
                dims.extend(_varints(value))
        elif field == 2 and wire == _WIRE_VARINT:
            data_type = int(value)
        elif field == 8:
            name = bytes(value).decode("utf-8", errors="replace")
        elif field == 9:
            raw = value
        elif field == 4:  # float_data (packed or repeated)
            if wire == _WIRE_LEN:
                float_data.extend(
                    struct.unpack(f"<{len(value) // 4}f", value))
            else:
                float_data.append(struct.unpack("<f", value)[0])
        elif field == 7:  # int64_data
            if wire == _WIRE_LEN:
                int64_data.extend(_varints(value))
            else:
                int64_data.append(int(value))
    dtype = _DTYPE.get(data_type)
    if dtype is None:
        raise FailedToLoadResource(
            f"initializer {name!r}: unsupported ONNX data type {data_type}")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype).copy()
    elif float_data:
        arr = np.asarray(float_data, dtype=np.float32)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=np.int64)
    else:
        arr = np.zeros(0, dtype=dtype)
    if dims:
        arr = arr.reshape(dims)
    return name, arr


def read_onnx_initializers(path: Union[str, Path]) -> dict[str, np.ndarray]:
    """Extract ``{initializer name: ndarray}`` from an ONNX file.

    Initializer-only walk — skips node decoding entirely (a ~100 MB voice
    file has thousands of nodes the plain weight path never needs); use
    :func:`read_onnx_graph` when node topology matters.
    """
    data = Path(path).read_bytes()
    out: dict[str, np.ndarray] = {}
    for field, wire, value in iter_fields(memoryview(data)):
        if field == 7 and wire == _WIRE_LEN:  # ModelProto.graph
            for gfield, gwire, gvalue in iter_fields(value):
                if gfield == 5 and gwire == _WIRE_LEN:  # initializer
                    name, arr = _decode_tensor(gvalue)
                    out[name] = arr
    if not out:
        raise FailedToLoadResource(
            f"{path}: no initializers found (not an ONNX model?)")
    return out


def to_f32(sd: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Upcast/downcast half/double tensors to float32 (shared by every
    ONNX import path)."""
    return {k: v.astype(np.float32) if v.dtype in (np.float16, np.float64)
            else v for k, v in sd.items()}


def _decode_attribute(buf) -> tuple[str, object]:
    """AttributeProto → (name, value) for the subset importers need.

    Fields (onnx.proto): name=1, f=2, i=3, s=4, t=5, ints=8.
    """
    name = ""
    value: object = None
    ints: list[int] = []

    def _signed(v: int) -> int:
        return v - (1 << 64) if v >= (1 << 63) else v

    for field, wire, raw in iter_fields(buf):
        if field == 1 and wire == _WIRE_LEN:
            name = bytes(raw).decode("utf-8", errors="replace")
        elif field == 2 and wire == _WIRE_32BIT:
            value = struct.unpack("<f", raw)[0]
        elif field == 3 and wire == _WIRE_VARINT:
            value = _signed(int(raw))
        elif field == 4 and wire == _WIRE_LEN:
            value = bytes(raw).decode("utf-8", errors="replace")
        elif field == 5 and wire == _WIRE_LEN:
            value = _decode_tensor(raw)[1]
        elif field == 8:
            if wire == _WIRE_VARINT:
                ints.append(_signed(int(raw)))
            else:
                ints.extend(_signed(v) for v in _varints(raw))
    if ints:
        value = ints
    return name, value


def _decode_node(buf) -> dict:
    """NodeProto → {op_type, inputs, outputs, attrs} (fields 1,2,4,5)."""
    node = {"op_type": "", "inputs": [], "outputs": [], "attrs": {}}
    for field, wire, raw in iter_fields(buf):
        if field == 1 and wire == _WIRE_LEN:
            node["inputs"].append(
                bytes(raw).decode("utf-8", errors="replace"))
        elif field == 2 and wire == _WIRE_LEN:
            node["outputs"].append(
                bytes(raw).decode("utf-8", errors="replace"))
        elif field == 4 and wire == _WIRE_LEN:
            node["op_type"] = bytes(raw).decode("utf-8", errors="replace")
        elif field == 5 and wire == _WIRE_LEN:
            k, v = _decode_attribute(raw)
            node["attrs"][k] = v
    return node


def read_onnx_graph(
        path: Union[str, Path],
) -> tuple[dict[str, np.ndarray], list[dict]]:
    """Extract ``({initializer name: ndarray}, [node dicts])`` from an ONNX
    file.  Nodes are returned in graph (topological) order; ``Constant``
    nodes contribute their tensor to the initializer map under their output
    name — ``torch.onnx.export`` with constant folding emits transformed
    weights (e.g. recurrent ``W/R/B``) this way.
    """
    data = Path(path).read_bytes()
    inits: dict[str, np.ndarray] = {}
    nodes: list[dict] = []
    for field, wire, value in iter_fields(memoryview(data)):
        if field == 7 and wire == _WIRE_LEN:  # ModelProto.graph
            for gfield, gwire, gvalue in iter_fields(value):
                if gfield == 5 and gwire == _WIRE_LEN:  # initializer
                    name, arr = _decode_tensor(gvalue)
                    inits[name] = arr
                elif gfield == 1 and gwire == _WIRE_LEN:  # node
                    node = _decode_node(gvalue)
                    nodes.append(node)
                    if (node["op_type"] == "Constant"
                            and node["outputs"]
                            and isinstance(node["attrs"].get("value"),
                                           np.ndarray)):
                        inits[node["outputs"][0]] = node["attrs"]["value"]
    if not inits:
        raise FailedToLoadResource(
            f"{path}: no initializers found (not an ONNX model?)")
    return inits, nodes


def recover_folded_conv_weights(inits: dict, nodes: list) -> dict:
    """Name anonymous folded conv weights after their conv's named bias.

    Graph optimizers (onnxsim, ORT offline optimization, newer
    ``torch.onnx.export`` folding) precompute the weight-norm
    ``g*v/||v||`` product into a single anonymous constant
    (``onnx::Conv_123``, ``/Mul_7_output_0``) and drop the named
    ``weight_g``/``weight_v`` initializers — but the conv's *bias* is not
    part of weight norm, so it keeps its parameter name.  For every
    Conv/ConvTranspose node whose weight input is an anonymous tensor and
    whose bias is a named ``{prefix}.bias``, register the weight tensor
    under ``{prefix}.weight`` so the state-dict mapper sees the layout it
    expects (ONNX Conv/ConvTranspose weight layouts equal torch's).
    """
    out = dict(inits)
    for n in nodes:
        if n["op_type"] not in ("Conv", "ConvTranspose"):
            continue
        ins = n["inputs"]
        if len(ins) < 3:
            continue
        w_name, b_name = ins[1], ins[2]
        if not b_name.endswith(".bias"):
            continue
        prefix = b_name[: -len(".bias")]
        if f"{prefix}.weight" in out or f"{prefix}.weight_v" in out:
            continue  # named weight (or recoverable g/v pair) already there
        anonymous = (w_name.startswith("/") or "::" in w_name
                     or not w_name.endswith((".weight", ".weight_v")))
        if anonymous and w_name in out:
            out[f"{prefix}.weight"] = out[w_name]
    return out


def resolve_identity_aliases(inits: dict, nodes: list) -> dict:
    """Materialize tensors routed through ``Identity`` nodes.

    ``torch.onnx.export`` deduplicates value-identical tensors: only one
    copy becomes an initializer and the other names are produced by
    ``Identity`` nodes (e.g. a fresh BatchNorm's ``running_mean`` aliasing
    its zero ``bias``).  Returns ``inits`` extended with one entry per
    resolvable Identity output.
    """
    out = dict(inits)
    pending = [n for n in nodes if n["op_type"] == "Identity"
               and n["inputs"] and n["outputs"]]
    progress = True
    while pending and progress:
        progress = False
        rest = []
        for n in pending:
            if n["inputs"][0] in out:
                out[n["outputs"][0]] = out[n["inputs"][0]]
                progress = True
            else:
                rest.append(n)
        pending = rest
    return out


def _merge_initializers(dicts: "list[tuple[str, dict]]") -> dict:
    """Merge per-file initializer maps (the streaming encoder/decoder
    split).  Real parameter names must agree when repeated; anonymous
    scope-generated names ("/Constant_output_0", "onnx::MatMul_12")
    legitimately collide across independent exports and are last-wins.
    """
    merged: dict = {}
    for label, d in dicts:
        for name, arr in d.items():
            prev = merged.get(name)
            anonymous = name.startswith("/") or "::" in name
            if (prev is not None and not anonymous
                    and (prev.shape != arr.shape
                         or not np.array_equal(prev, arr))):
                raise FailedToLoadResource(
                    f"initializer {name!r} differs between the merged "
                    f"ONNX files (last: {label})")
            merged[name] = arr
    return merged


def import_onnx_weights(path: Union[str, Path, "tuple", "list"],
                        hp: VitsHyperParams, *,
                        n_vocab: int, n_speakers: int = 1) -> dict:
    """ONNX initializers → native param pytree.

    ``torch.onnx.export`` keeps parameter names for initializers, so the
    state-dict mapper applies directly.  Weight-norm is usually already
    fused in exports (piper removes it); if ``weight_g/v`` pairs survive,
    the mapper fuses them.

    ``path`` may be a sequence of files whose initializer sets partition
    one model — the streaming voice layout's ``encoder.onnx`` +
    ``decoder.onnx`` (``piper/src/lib.rs:90-96``).
    """
    from .import_torch import state_dict_to_params, strip_prefix

    paths = list(path) if isinstance(path, (tuple, list)) else [path]
    sd = to_f32(_merge_initializers(
        [(str(p), read_onnx_initializers(p)) for p in paths]))
    try:
        return state_dict_to_params(strip_prefix(sd), hp, n_vocab=n_vocab,
                                    n_speakers=n_speakers)
    except FailedToLoadResource:
        # torch.onnx.export deduplicates value-identical tensors behind
        # Identity nodes (e.g. untouched LayerNorm gammas), and graph
        # optimizers fold weight-norm products into anonymous constants;
        # retry with the full graph walk resolving both
        resolved = []
        for p in paths:
            inits, nodes = read_onnx_graph(p)
            inits = resolve_identity_aliases(inits, nodes)
            inits = recover_folded_conv_weights(inits, nodes)
            resolved.append((str(p), inits))
        sd = to_f32(_merge_initializers(resolved))
        return state_dict_to_params(strip_prefix(sd), hp, n_vocab=n_vocab,
                                    n_speakers=n_speakers)
