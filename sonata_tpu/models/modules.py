"""Neural building blocks for VITS, as pure JAX functions over param pytrees.

Design notes (TPU-first, not a port):

- The reference never contains this math — it executes a black-box ONNX graph
  via onnxruntime (``crates/sonata/models/piper/src/lib.rs:342-399``).  These
  modules re-implement the *architecture* of Piper-flavor VITS (text encoder
  with windowed relative attention, stochastic duration predictor over
  rational-quadratic-spline flows, residual-coupling flow with WaveNet
  blocks, HiFi-GAN decoder) natively in JAX so XLA owns fusion/layout.
- Everything is ``[batch, time, channels]`` (NTC): the lane dimension maps to
  channels, convs lower to MXU matmuls, and no transposes are needed between
  attention and conv blocks.
- Params are plain nested dicts (a JAX pytree).  Each block has
  ``init_*(rng, ...) -> params`` and a pure ``apply`` function, so the whole
  model jits/pjits and weights import cleanly from Piper torch checkpoints.
- Masks are explicit ``[B, T, 1]`` float tensors; all shapes static — no
  data-dependent control flow anywhere (XLA traces once per bucket).
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.gate import fused_gate as gate_op

Params = dict

LRELU_SLOPE = 0.1


# ---------------------------------------------------------------------------
# initializers
# ---------------------------------------------------------------------------

def _normal(rng, shape, std=0.02):
    return jax.random.normal(rng, shape, dtype=jnp.float32) * std


def _conv_init(rng, k, c_in, c_out):
    # kaiming-uniform-ish fan-in scaling, matching torch conv defaults
    bound = 1.0 / math.sqrt(c_in * k)
    w_rng, b_rng = jax.random.split(rng)
    return {
        "w": jax.random.uniform(w_rng, (k, c_in, c_out), jnp.float32, -bound, bound),
        "b": jax.random.uniform(b_rng, (c_out,), jnp.float32, -bound, bound),
    }


# ---------------------------------------------------------------------------
# conv primitives (NTC layout)
# ---------------------------------------------------------------------------

def conv1d(x, p, *, dilation: int = 1, stride: int = 1,
           padding: str | int = "SAME"):
    """1-D convolution, ``x: [B, T, C_in]``, weight ``[K, C_in, C_out]``."""
    if isinstance(padding, int):
        pad = [(padding, padding)]
    elif padding == "SAME":
        k_eff = (p["w"].shape[0] - 1) * dilation + 1
        pad = [(k_eff // 2, k_eff - 1 - k_eff // 2)]
    else:
        pad = padding
    y = lax.conv_general_dilated(
        x, p["w"], window_strides=(stride,), padding=pad,
        rhs_dilation=(dilation,),
        dimension_numbers=("NHC", "HIO", "NHC"),
    )
    return y + p["b"]


def conv_transpose1d(x, p, *, stride: int, padding: int):
    """Transposed 1-D conv matching torch ``ConvTranspose1d`` semantics.

    ``x: [B, T, C_in]``, weight stored ``[K, C_in, C_out]``.  Output length is
    ``(T-1)*stride - 2*padding + K`` — identical to torch, so HiFi-GAN
    upsample stacks produce exactly ``T * prod(rates)`` samples when
    ``padding=(K-stride)//2`` with even ``K-stride``.

    When the HiFi-GAN geometry holds (``K - stride == 2*padding``) this
    lowers to the sub-pixel form (:func:`conv_transpose1d_subpixel`): the
    textbook ``lhs_dilation`` lowering makes the MXU multiply mostly
    zeros — ``stride-1`` of every ``stride`` dilated input positions are
    stuffing — an ~8x FLOP waste at Piper's first upsample stage.
    """
    k = p["w"].shape[0]
    if (k - stride == 2 * padding and stride > 1
            and os.environ.get("SONATA_TCONV", "subpixel") != "naive"):
        return conv_transpose1d_subpixel(x, p, stride=stride, padding=padding)
    y = lax.conv_general_dilated(
        x, jnp.flip(p["w"], 0), window_strides=(1,),
        padding=[(k - 1 - padding, k - 1 - padding)],
        lhs_dilation=(stride,),
        dimension_numbers=("NHC", "HIO", "NHC"),
    )
    return y + p["b"]


def conv_transpose1d_subpixel(x, p, *, stride: int, padding: int):
    """Transposed conv as a dense conv + depth-to-space (exact).

    Writing output index ``n = stride*b + r``, the transposed conv is, per
    phase ``r``, a small dense conv over the *un-dilated* input:

        y[s*b + r] = sum_d x[b + d] * w_flip[s*d + (K-1-pad-r)]

    so all ``stride`` phases stack into one conv with ``stride * C_out``
    output channels followed by a reshape — every MAC works on real data.
    Requires the exact-upsample geometry ``(T-1)s - 2p + K == T*s``, i.e.
    ``K - s == 2p`` (all Piper/HiFi-GAN stages satisfy this).
    """
    w = p["w"]  # [K, C_in, C_out]
    k, c_in, c_out = w.shape
    s = stride
    wf = jnp.flip(w, 0)
    # tap range over d for any phase r: j = s*d + (k-1-padding-r) in [0, k)
    cs = [k - 1 - padding - r for r in range(s)]
    d_lo = min(math.ceil(-c / s) for c in cs)
    d_hi = max(math.floor((k - 1 - c) / s) for c in cs)
    taps = d_hi - d_lo + 1
    # gather kernel: [taps, C_in, s, C_out], zero where j falls outside
    wsub = jnp.zeros((taps, c_in, s, c_out), w.dtype)
    for r in range(s):
        c = cs[r]
        for d in range(d_lo, d_hi + 1):
            j = s * d + c
            if 0 <= j < k:
                wsub = wsub.at[d - d_lo, :, r, :].set(wf[j])
    wsub = wsub.reshape(taps, c_in, s * c_out)
    y = lax.conv_general_dilated(
        x, wsub, window_strides=(1,), padding=[(-d_lo, d_hi)],
        dimension_numbers=("NHC", "HIO", "NHC"),
    )  # [B, T, s*C_out]
    b_, t_, _ = y.shape
    y = y.reshape(b_, t_ * s, c_out)
    return y + p["b"]


def layer_norm(x, p, eps: float = 1e-5):
    """LayerNorm over channels (last dim)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * p["gamma"] + p["beta"]


def init_layer_norm(c):
    return {"gamma": jnp.ones((c,)), "beta": jnp.zeros((c,))}


# ---------------------------------------------------------------------------
# windowed relative-position multi-head attention (VITS text encoder)
# ---------------------------------------------------------------------------

def init_rel_attention(rng, channels: int, n_heads: int, window: int):
    head = channels // n_heads
    rngs = jax.random.split(rng, 6)
    std = (head ** -0.5)
    return {
        "q": _conv_init(rngs[0], 1, channels, channels),
        "k": _conv_init(rngs[1], 1, channels, channels),
        "v": _conv_init(rngs[2], 1, channels, channels),
        "o": _conv_init(rngs[3], 1, channels, channels),
        # learned relative embeddings over [-window, window]
        "emb_rel_k": _normal(rngs[4], (1, 2 * window + 1, head), std),
        "emb_rel_v": _normal(rngs[5], (1, 2 * window + 1, head), std),
    }


def _rel_to_abs(x):
    """[B*H, T, 2T-1] relative-indexed logits → [B*H, T, T] absolute."""
    b, t, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, 1)))
    x = x.reshape(b, t * 2 * t)
    x = jnp.pad(x, ((0, 0), (0, t - 1)))
    x = x.reshape(b, t + 1, 2 * t - 1)
    return x[:, :t, t - 1:]


def _abs_to_rel(x):
    """[B*H, T, T] absolute attention weights → [B*H, T, 2T-1] relative."""
    b, t, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, t - 1)))
    x = x.reshape(b, t * (2 * t - 1))
    x = jnp.pad(x, ((0, 0), (t, 0)))
    x = x.reshape(b, t, 2 * t)
    return x[:, :, 1:]


def _rel_embeddings(emb, window, t):
    """Slice/pad the learned [-window, window] table to [2T-1] positions."""
    pad = max(t - window - 1, 0)
    start = max(window + 1 - t, 0)
    emb = jnp.pad(emb, ((0, 0), (pad, pad), (0, 0)))
    return lax.dynamic_slice_in_dim(emb, start, 2 * t - 1, axis=1)


def rel_attention(x, mask, p, *, n_heads: int, window: int):
    """Self-attention with learned relative position embeddings, window
    ±``window`` (VITS text encoder uses window=4).

    ``x: [B, T, C]``, ``mask: [B, T, 1]`` (1 = valid).
    """
    b, t, c = x.shape
    head = c // n_heads
    q = conv1d(x, p["q"])
    k = conv1d(x, p["k"])
    v = conv1d(x, p["v"])

    def split(u):  # [B, T, C] -> [B*H, T, head]
        return u.reshape(b, t, n_heads, head).transpose(0, 2, 1, 3).reshape(
            b * n_heads, t, head
        )

    q, k, v = split(q), split(k), split(v)
    scale = head ** -0.5
    logits = jnp.einsum("btd,bsd->bts", q * scale, k)
    # relative key contribution
    rel_k = _rel_embeddings(p["emb_rel_k"], window, t)  # [1, 2T-1, head]
    rel_logits = jnp.einsum("btd,msd->bts", q * scale, rel_k)
    logits = logits + _rel_to_abs(rel_logits)

    attn_mask = (mask[:, None, :, 0] * mask[:, :, None, 0])  # [B, T, T]
    attn_mask = jnp.repeat(attn_mask, n_heads, axis=0).reshape(b * n_heads, t, t)
    logits = jnp.where(attn_mask > 0, logits, -1e4)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bts,bsd->btd", weights, v)
    # relative value contribution
    rel_v = _rel_embeddings(p["emb_rel_v"], window, t)  # [1, 2T-1, head]
    out = out + jnp.einsum("btm,bmd->btd", _abs_to_rel(weights), rel_v)

    out = out.reshape(b, n_heads, t, head).transpose(0, 2, 1, 3).reshape(b, t, c)
    return conv1d(out, p["o"]) * mask


# ---------------------------------------------------------------------------
# conv feed-forward (VITS encoder FFN)
# ---------------------------------------------------------------------------

def init_ffn(rng, channels, filter_channels, kernel):
    r1, r2 = jax.random.split(rng)
    return {
        "c1": _conv_init(r1, kernel, channels, filter_channels),
        "c2": _conv_init(r2, kernel, filter_channels, channels),
    }


def ffn(x, mask, p):
    y = conv1d(x * mask, p["c1"])
    y = jax.nn.relu(y)
    return conv1d(y * mask, p["c2"]) * mask


# ---------------------------------------------------------------------------
# transformer encoder stack
# ---------------------------------------------------------------------------

def init_transformer(rng, *, channels, filter_channels, n_heads, n_layers,
                     kernel, window):
    layers = []
    for i in range(n_layers):
        r = jax.random.fold_in(rng, i)
        r1, r2 = jax.random.split(r)
        layers.append({
            "attn": init_rel_attention(r1, channels, n_heads, window),
            "ln1": init_layer_norm(channels),
            "ffn": init_ffn(r2, channels, filter_channels, kernel),
            "ln2": init_layer_norm(channels),
        })
    return {"layers": layers}


def transformer(x, mask, p, *, n_heads, window):
    """Post-norm transformer: x = LN(x + attn(x)); x = LN(x + ffn(x))."""
    x = x * mask
    for layer in p["layers"]:
        y = rel_attention(x, mask, layer["attn"], n_heads=n_heads, window=window)
        x = layer_norm(x + y, layer["ln1"])
        y = ffn(x, mask, layer["ffn"])
        x = layer_norm(x + y, layer["ln2"])
    return x * mask


def transformer_seq_parallel(x, mask, p, *, n_heads, window, mesh):
    """The same post-norm encoder stack, SPMD over the mesh's ``seq`` axis.

    Long inputs shard along time: attention runs as a ring
    (:func:`sonata_tpu.parallel.ring.ring_rel_attention_sharded`, exact —
    including the windowed relative embeddings, which only couple
    ring-adjacent blocks since |s−t| ≤ window), and the FFN's kernel-3
    convs see their neighbors' boundary columns via a halo exchange.  All
    other ops are per-position and stay local.  Numerics match
    :func:`transformer` (same math, blockwise softmax).

    ``x: [B, T, C]`` with ``T`` divisible by the seq-axis size.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, SEQ_AXIS
    from ..parallel.ring import halo_exchange, ring_rel_attention_sharded
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    def attn_local(x_loc, mask_loc, lp):
        b, t, c = x_loc.shape
        head = c // n_heads

        def split(u):  # [B, T, C] → [B, H, T, head]
            return u.reshape(b, t, n_heads, head).transpose(0, 2, 1, 3)

        out = ring_rel_attention_sharded(
            split(conv1d(x_loc, lp["q"])),
            split(conv1d(x_loc, lp["k"])),
            split(conv1d(x_loc, lp["v"])),
            mask_loc[..., 0],
            lp["emb_rel_k"][0], lp["emb_rel_v"][0], window=window)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, c)
        return conv1d(out, lp["o"]) * mask_loc

    def conv_halo(x_loc, cp):
        k = cp["w"].shape[0]
        ext = halo_exchange(x_loc, k // 2, k - 1 - k // 2)
        return conv1d(ext, cp, padding=0)

    def inner(x_loc, mask_loc, params):
        x_loc = x_loc * mask_loc
        for layer in params["layers"]:
            y = attn_local(x_loc, mask_loc, layer["attn"])
            x_loc = layer_norm(x_loc + y, layer["ln1"])
            y = conv_halo(x_loc * mask_loc, layer["ffn"]["c1"])
            y = jax.nn.relu(y)
            y = conv_halo(y * mask_loc, layer["ffn"]["c2"]) * mask_loc
            x_loc = layer_norm(x_loc + y, layer["ln2"])
        return x_loc * mask_loc

    spec_x = P(DATA_AXIS, SEQ_AXIS, None)
    fn = shard_map(inner, mesh=mesh, in_specs=(spec_x, spec_x, P()),
                   out_specs=spec_x)
    return fn(x, mask, p)


# ---------------------------------------------------------------------------
# WaveNet block (used by the coupling flow)
# ---------------------------------------------------------------------------

def init_wn(rng, *, hidden, kernel, dilation_rate, n_layers, gin_channels=0):
    in_layers, res_skip = [], []
    for i in range(n_layers):
        r = jax.random.fold_in(rng, i)
        r1, r2 = jax.random.split(r)
        dil = dilation_rate ** i
        in_layers.append(_conv_init(r1, kernel, hidden, 2 * hidden))
        out_ch = 2 * hidden if i < n_layers - 1 else hidden
        res_skip.append(_conv_init(r2, 1, hidden, out_ch))
    p = {"in": in_layers, "res_skip": res_skip}
    if gin_channels:
        p["cond"] = _conv_init(jax.random.fold_in(rng, 999), 1, gin_channels,
                               2 * hidden * n_layers)
    return p


def wn(x, mask, p, *, kernel, dilation_rate, n_layers, g=None, conv=None):
    """Non-causal WaveNet: dilated convs, gated tanh units, residual+skip.

    ``x: [B, T, H]``; ``g: [B, 1, gin]`` speaker conditioning or None.
    The gate runs through :func:`sonata_tpu.ops.gate.fused_gate` — a Pallas
    kernel on TPU, plain jnp elsewhere.  ``conv`` overrides the dilated
    conv primitive (sequence-sharded callers inject a halo-exchange
    version); pointwise convs never need halos and stay plain.
    """
    conv = conv or conv1d
    hidden = x.shape[-1]
    output = jnp.zeros_like(x)
    if g is not None and "cond" in p:
        g_all = conv1d(g, p["cond"])  # [B, 1, 2*H*n_layers]
    for i in range(n_layers):
        x_in = conv(x, p["in"][i], dilation=dilation_rate ** i)
        g_l = None
        if g is not None and "cond" in p:
            g_l = lax.dynamic_slice_in_dim(g_all, i * 2 * hidden, 2 * hidden, axis=2)
        acts = gate_op(x_in, g_l)
        rs = conv1d(acts, p["res_skip"][i])
        if i < n_layers - 1:
            x = (x + rs[..., :hidden]) * mask
            output = output + rs[..., hidden:]
        else:
            output = output + rs
    return output * mask


# ---------------------------------------------------------------------------
# DDSConv — dilated depth-separable convs (duration predictor backbone)
# ---------------------------------------------------------------------------

def init_dds_conv(rng, *, channels, kernel, n_layers):
    layers = []
    for i in range(n_layers):
        r = jax.random.fold_in(rng, i)
        r1, r2 = jax.random.split(r)
        layers.append({
            # depthwise stored [K, 1, C] and applied with feature_group_count
            "dw": {"w": _normal(r1, (kernel, 1, channels),
                                1.0 / math.sqrt(kernel)),
                   "b": jnp.zeros((channels,))},
            "pw": _conv_init(r2, 1, channels, channels),
            "ln1": init_layer_norm(channels),
            "ln2": init_layer_norm(channels),
        })
    return {"layers": layers}


def dds_conv(x, mask, p, *, kernel: int, g=None):
    if g is not None:
        x = x + g
    c = x.shape[-1]
    for i, layer in enumerate(p["layers"]):
        dilation = kernel ** i
        k_eff = (kernel - 1) * dilation + 1
        pad = k_eff // 2
        y = lax.conv_general_dilated(
            x * mask, layer["dw"]["w"], window_strides=(1,),
            padding=[(pad, k_eff - 1 - pad)], rhs_dilation=(dilation,),
            dimension_numbers=("NHC", "HIO", "NHC"),
            feature_group_count=c,
        ) + layer["dw"]["b"]
        y = jax.nn.gelu(layer_norm(y, layer["ln1"]))
        y = conv1d(y, layer["pw"])
        y = jax.nn.gelu(layer_norm(y, layer["ln2"]))
        x = x + y
    return x * mask


# ---------------------------------------------------------------------------
# rational-quadratic spline (inverse mode) — ConvFlow transform
# ---------------------------------------------------------------------------

DEFAULT_MIN_BIN_WIDTH = 1e-3
DEFAULT_MIN_BIN_HEIGHT = 1e-3
DEFAULT_MIN_DERIVATIVE = 1e-3


def rational_quadratic_spline_inverse(
    y, unnorm_widths, unnorm_heights, unnorm_derivs, *, tail_bound: float
):
    """Inverse pass of an unconstrained monotonic rational-quadratic spline
    (Durkan et al., Neural Spline Flows).  Identity outside
    ``[-tail_bound, tail_bound]``.

    All inputs broadcast elementwise with a trailing ``num_bins`` dim on the
    parameter tensors.  Fully vectorized; no data-dependent control flow, so
    it jits to a single fused XLA computation.
    """
    num_bins = unnorm_widths.shape[-1]
    inside = (y >= -tail_bound) & (y <= tail_bound)

    widths = jax.nn.softmax(unnorm_widths, axis=-1)
    widths = DEFAULT_MIN_BIN_WIDTH + (1 - DEFAULT_MIN_BIN_WIDTH * num_bins) * widths
    cumwidths = jnp.cumsum(widths, axis=-1)
    cumwidths = jnp.pad(cumwidths, [(0, 0)] * (cumwidths.ndim - 1) + [(1, 0)])
    cumwidths = (2 * tail_bound) * cumwidths - tail_bound
    widths = cumwidths[..., 1:] - cumwidths[..., :-1]

    derivs = DEFAULT_MIN_DERIVATIVE + jax.nn.softplus(unnorm_derivs)
    # boundary derivatives pinned to 1 (linear tails)
    pad_val = math.log(math.exp(1 - DEFAULT_MIN_DERIVATIVE) - 1)
    derivs = jnp.concatenate(
        [jnp.full_like(derivs[..., :1], DEFAULT_MIN_DERIVATIVE
                       + jax.nn.softplus(jnp.float32(pad_val))),
         derivs,
         jnp.full_like(derivs[..., :1], DEFAULT_MIN_DERIVATIVE
                       + jax.nn.softplus(jnp.float32(pad_val)))],
        axis=-1,
    )

    heights = jax.nn.softmax(unnorm_heights, axis=-1)
    heights = DEFAULT_MIN_BIN_HEIGHT + (1 - DEFAULT_MIN_BIN_HEIGHT * num_bins) * heights
    cumheights = jnp.cumsum(heights, axis=-1)
    cumheights = jnp.pad(cumheights, [(0, 0)] * (cumheights.ndim - 1) + [(1, 0)])
    cumheights = (2 * tail_bound) * cumheights - tail_bound
    heights = cumheights[..., 1:] - cumheights[..., :-1]

    y_in = jnp.clip(y, -tail_bound, tail_bound)
    # locate bin by cumheights (inverse mode): one-hot over bins
    idx = jnp.sum((y_in[..., None] >= cumheights[..., :-1]).astype(jnp.int32),
                  axis=-1) - 1
    idx = jnp.clip(idx, 0, num_bins - 1)

    def gather(t):
        return jnp.take_along_axis(t, idx[..., None], axis=-1)[..., 0]

    in_cumwidths = gather(cumwidths[..., :-1])
    in_widths = gather(widths)
    in_cumheights = gather(cumheights[..., :-1])
    in_heights = gather(heights)
    in_delta = in_heights / in_widths
    in_d = gather(derivs[..., :-1])
    in_d_plus = gather(derivs[..., 1:])

    # solve the quadratic for xi (Durkan et al. eq. 6-8, inverse)
    rel_y = y_in - in_cumheights
    term = rel_y * (in_d + in_d_plus - 2 * in_delta)
    a = in_heights * (in_delta - in_d) + term
    b = in_heights * in_d - term
    c = -in_delta * rel_y
    disc = b * b - 4 * a * c
    disc = jnp.maximum(disc, 0.0)
    xi = (2 * c) / (-b - jnp.sqrt(disc))
    xi = jnp.clip(xi, 0.0, 1.0)
    x_val = xi * in_widths + in_cumwidths

    # log|det d y / d x| (forward direction), negated by the caller if needed
    denom = in_delta + (in_d + in_d_plus - 2 * in_delta) * xi * (1 - xi)
    nom = in_delta ** 2 * (
        in_d_plus * xi ** 2 + 2 * in_delta * xi * (1 - xi) + in_d * (1 - xi) ** 2
    )
    logabsdet = jnp.log(jnp.maximum(nom, 1e-12)) - 2 * jnp.log(
        jnp.maximum(denom, 1e-12)
    )

    x_out = jnp.where(inside, x_val, y)
    logabsdet = jnp.where(inside, logabsdet, 0.0)
    return x_out, logabsdet
