"""Decoder-arm options: fused decode epilogue + int8 weight-only quant.

Two independent, env-gated speedups for the HiFi-GAN decode path, both
measured by ``tools/bench_cpu.py`` arms and parity-gated against float32
(tests/test_decode_opts.py):

**Fused decode epilogue** (``SONATA_FUSED_EPILOGUE=pallas|lax|off``,
default ``lax``): the streaming pipeline used to ship every decoded
window back to the host as float32 and run the per-chunk epilogue there
— slice to the emitted range, crossfade taper
(:data:`~sonata_tpu.models.chunker.CROSSFADE_SAMPLES`), i16 conversion
at output time.  That host work sits directly on TTFB and per-chunk
latency, and the f32 transfer is twice the bytes the audio needs.  The
fused arm runs taper + peak-scaled i16 quantization *inside the same
device program as the window decode* (one jitted executable per
(width, batch rung) — see ``PiperVoice._decode_windows_fused_fn``), so
one dispatch returns quantized, already-tapered samples plus the
per-row peak for exact host-side dequantization.  ``lax`` composes the
epilogue from jnp ops (portable, the default everywhere); ``pallas``
lowers the epilogue to a Pallas TPU kernel (accelerator-targeted — on
a CPU backend it runs in interpret mode, which tests use for parity;
production CPU deployments should keep ``lax``); ``off`` restores the
host-side epilogue.

**int8 weight-only decoder quantization** (``SONATA_DECODE_QUANT=int8``,
default off): per-output-channel symmetric int8 quantization of every
decoder conv weight, dequantized *in kernel* (the int8 weights ship to
the device; the jitted program rescales them to f32/bf16 right before
each conv — activations keep full precision).  Quarters the decoder
weight HBM traffic; gated by the spectral-distance parity test against
f32.

This module is the single reader of both knobs (the sonata-lint knob
registry's split-default rule).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import OperationError

# ---------------------------------------------------------------------------
# knob resolution (single-module defaults)
# ---------------------------------------------------------------------------

FUSED_EPILOGUE_ENV = "SONATA_FUSED_EPILOGUE"
FUSED_EPILOGUE_MODES = ("pallas", "lax", "off")

DECODE_QUANT_ENV = "SONATA_DECODE_QUANT"


def resolve_fused_epilogue(setting: Optional[str] = None,
                           env: Optional[dict] = None) -> str:
    """``pallas`` | ``lax`` | ``off``; a typo fails loudly (the
    SONATA_BATCH_MODE contract: a fleet silently running the wrong
    epilogue arm is a perf regression nobody would see)."""
    if setting is None:
        env = os.environ if env is None else env
        setting = env.get(FUSED_EPILOGUE_ENV, "").strip().lower()
    if not setting:
        return "lax"
    if setting not in FUSED_EPILOGUE_MODES:
        raise OperationError(
            f"{FUSED_EPILOGUE_ENV}={setting!r} is not one of "
            f"{'/'.join(FUSED_EPILOGUE_MODES)}")
    return setting


def resolve_decode_quant(setting: Optional[str] = None,
                         env: Optional[dict] = None) -> Optional[str]:
    """``int8`` or None (off); a typo fails loudly."""
    if setting is None:
        env = os.environ if env is None else env
        setting = env.get(DECODE_QUANT_ENV, "").strip().lower()
    if setting in ("", "off", "0"):
        return None
    if setting == "int8":
        return "int8"
    raise OperationError(
        f"{DECODE_QUANT_ENV}={setting!r} is not one of int8/off")


# ---------------------------------------------------------------------------
# fused epilogue: crossfade taper + peak-scaled i16 quantize, on device
# ---------------------------------------------------------------------------

def _taper_gains(idx, lo, hi, fade: int):
    """Per-sample gain replicating the host epilogue exactly: quarter-sine
    fade-in over the first ``min(fade, L)`` samples of the emitted range
    [lo, hi), quarter-cosine fade-out over the last — both applied
    (multiplicatively, like ``AudioSamples.crossfade``) when the range is
    shorter than ``2*fade`` — and zero outside the range (the host
    slices it away; zeroing makes the masked peak exact)."""
    length = hi - lo
    n = jnp.minimum(jnp.int32(fade), length)
    nf = jnp.maximum(n, 1).astype(jnp.float32)
    half_pi = jnp.float32(np.pi / 2)
    j = (idx - lo).astype(jnp.float32)
    k = (idx - (hi - n)).astype(jnp.float32)
    in_gain = jnp.where(idx - lo < n, jnp.sin(j / nf * half_pi), 1.0)
    out_gain = jnp.where(idx >= hi - n, jnp.cos(k / nf * half_pi), 1.0)
    mask = ((idx >= lo) & (idx < hi)).astype(jnp.float32)
    return in_gain * out_gain * mask


def _quantize_rows(tapered):
    """Peak-scaled i16, the ``_decode_quantize`` contract: per-row peak
    ships back so the host restores original amplitudes exactly (modulo
    the i16 grid), with the same 0.01 silence floor."""
    peak = jnp.max(jnp.abs(tapered), axis=-1)
    scale = 32767.0 / jnp.maximum(peak, 0.01)[..., None]
    q = jnp.clip(tapered * scale, -32768.0, 32767.0).astype(jnp.int16)
    return q, peak


def _lax_epilogue(wav, lo, hi, fade: int):
    """jnp composition of the fused epilogue (the default arm).

    ``wav``: [B, S] float32 decoded windows; ``lo``/``hi``: [B] int32
    sample bounds of each row's emitted slice.  Returns
    (i16 [B, S], peak [B])."""
    idx = jnp.arange(wav.shape[-1], dtype=jnp.int32)[None, :]
    gains = _taper_gains(idx, lo[:, None], hi[:, None], fade)
    return _quantize_rows(wav * gains)


def _pallas_epilogue_kernel(fade: int, lo_ref, hi_ref, wav_ref,
                            q_ref, peak_ref):
    """One grid step per batch row: taper + quantize a [1, S] window.

    Scalars (lo/hi/peak) live in SMEM; the window rides VMEM.  The math
    is the shared :func:`_taper_gains`/:func:`_quantize_rows` pair, so
    the two arms cannot drift."""
    wav = wav_ref[...]                                   # [1, S]
    idx = jax.lax.broadcasted_iota(jnp.int32, wav.shape, 1)
    gains = _taper_gains(idx, lo_ref[0], hi_ref[0], fade)
    q, peak = _quantize_rows(wav * gains)
    q_ref[...] = q
    peak_ref[0, 0] = peak[0]


def _pallas_epilogue(wav, lo, hi, fade: int):
    """Pallas-lowered epilogue (accelerator arm).  On a CPU backend the
    kernel runs in interpret mode — correct but slow, intended only for
    the parity tests; production CPU keeps the ``lax`` arm."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s = wav.shape
    kernel = functools.partial(_pallas_epilogue_kernel, fade)
    q, peak = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, s), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, s), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s), jnp.int16),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ],
        interpret=jax.default_backend() == "cpu",
    )(lo, hi, wav)
    return q, peak[:, 0]


def fused_epilogue(wav, lo, hi, fade: int, *, mode: str):
    """Dispatch to the requested arm (``mode`` is static at trace time:
    one compiled program per arm, never a runtime branch)."""
    if mode == "pallas":
        return _pallas_epilogue(wav, lo, hi, fade)
    return _lax_epilogue(wav, lo, hi, fade)


def dequantize_chunk(q, peak):
    """Host-side inverse of the fused quantize for one row: restores the
    pre-quantization float32 amplitudes (the exact ``_finish_batch``
    dequantization contract, same 0.01 floor)."""
    return np.asarray(q, np.float32) * (max(float(peak), 0.01) / 32767.0)


# ---------------------------------------------------------------------------
# int8 weight-only decoder quantization
# ---------------------------------------------------------------------------

def _map_convs(tree, fn):
    """Apply ``fn`` to every conv-param dict (the {w, b} /
    {w_q, w_scale, b} leaves) of a decoder subtree, preserving
    structure."""
    if isinstance(tree, dict):
        if "w" in tree or "w_q" in tree:
            return fn(tree)
        return {k: _map_convs(v, fn) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_map_convs(v, fn) for v in tree]
    return tree


def quantize_decoder(pd):
    """Per-output-channel symmetric int8 of every decoder conv weight.

    Weights are stored [K, C_in, C_out]; each output channel gets its
    own scale (``max|w| / 127`` over the kernel and input axes), so a
    quiet channel is not crushed by a loud one's range.  Biases stay
    float32 (tiny, and additive error does not amortize).  Host-side
    numpy, once, at voice load."""
    def q_conv(p):
        if "w_q" in p:
            return p  # already quantized (replica copies)
        w = np.asarray(p["w"], np.float32)
        scale = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)),
                       keepdims=True) / 127.0
        scale = np.maximum(scale, 1e-12).astype(np.float32)
        wq = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
        out = {"w_q": jnp.asarray(wq), "w_scale": jnp.asarray(scale)}
        if "b" in p:
            out["b"] = p["b"]
        return out

    return _map_convs(pd, q_conv)


def decoder_is_quantized(pd) -> bool:
    hit = []

    def probe(p):
        if "w_q" in p:
            hit.append(True)
        return p

    _map_convs(pd, probe)
    return bool(hit)


def dequantize_decoder(pd):
    """Structural inverse, run *inside* the jitted decode program: int8
    weights rescale to float32 right before their conv (weight-only —
    activations never quantize).  A plain f32 tree passes through
    untouched, so every decode path calls this unconditionally."""
    if not decoder_is_quantized(pd):
        return pd

    def dq(p):
        if "w_q" not in p:
            return p
        out = {"w": p["w_q"].astype(jnp.float32) * p["w_scale"]}
        if "b" in p:
            out["b"] = p["b"]
        return out

    return _map_convs(pd, dq)
