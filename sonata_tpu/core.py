"""Core abstractions: the model plug-in contract, error types, and phoneme
containers.

This is the TPU-native analogue of the reference's ``sonata-core`` crate
(``crates/sonata/core/src/lib.rs:20-131``): a model-agnostic contract that the
synthesizer layer talks to, so new model families can plug in without touching
orchestration or frontends.  Where the reference uses a Rust trait with
``Box<dyn Any>`` type-erased synthesis configs (``core/src/lib.rs:88-90``),
we use a Python protocol with ``object``-typed configs — the same degree of
model-agnosticism, idiomatically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, Optional, Protocol, runtime_checkable


# ---------------------------------------------------------------------------
# Errors — mirrors SonataError (reference core/src/lib.rs:20-24)
# ---------------------------------------------------------------------------

class SonataError(Exception):
    """Base error for the framework."""


class FailedToLoadResource(SonataError):
    """A model file, config, or data directory could not be loaded."""


class PhonemizationError(SonataError):
    """Text could not be converted to phonemes."""


class OperationError(SonataError):
    """A synthesis or post-processing operation failed."""


# ---------------------------------------------------------------------------
# Phonemes — one IPA string per sentence (reference core/src/lib.rs:53-79)
# ---------------------------------------------------------------------------

class Phonemes:
    """A list of sentences, each a single string of IPA phonemes.

    The reference models this as a newtype over ``Vec<String>``
    (``core/src/lib.rs:53``).  Sentence boundaries come from the phonemizer's
    clause splitting, so no single device program ever sees more than one
    sentence of text.
    """

    __slots__ = ("sentences",)

    def __init__(self, sentences: Optional[list[str]] = None):
        self.sentences: list[str] = list(sentences or [])

    def append(self, sentence: str) -> None:
        self.sentences.append(sentence)

    def extend(self, other: "Phonemes") -> None:
        self.sentences.extend(other.sentences)

    def to_string(self, sep: str = " ") -> str:
        return sep.join(self.sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self.sentences)

    def __len__(self) -> int:
        return len(self.sentences)

    def __getitem__(self, i):
        return self.sentences[i]

    def __repr__(self) -> str:
        return f"Phonemes({self.sentences!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Phonemes) and self.sentences == other.sentences


# ---------------------------------------------------------------------------
# Audio metadata (reference re-exports AudioInfo from audio-ops;
# core/src/lib.rs:7-12)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AudioInfo:
    sample_rate: int
    num_channels: int = 1
    sample_width: int = 2  # bytes per sample (16-bit PCM)


# ---------------------------------------------------------------------------
# Model protocol — the TPU-era SonataModel trait
# (reference core/src/lib.rs:82-131)
# ---------------------------------------------------------------------------

@runtime_checkable
class Model(Protocol):
    """The model plug-in contract.

    Mirrors the reference ``SonataModel`` trait surface
    (``core/src/lib.rs:83-130``): audio info, phonemization, batch + single
    sentence synthesis, type-erased synthesis-config get/set, speaker-map
    helpers, a streaming-capability flag and a default-error streaming
    method.  Concrete implementations live in ``sonata_tpu.models``.
    """

    def audio_output_info(self) -> AudioInfo:  # core/src/lib.rs:83
        ...

    def phonemize_text(self, text: str) -> Phonemes:  # core/src/lib.rs:84
        ...

    def speak_batch(self, phoneme_batches: list[str],
                    speakers: Optional[list[Optional[int]]] = None,
                    scales: Optional[list[Any]] = None) -> list["Audio"]:
        # core/src/lib.rs:85 — but unlike the reference's speak_batch
        # (piper/src/lib.rs:425-437, a sequential loop), implementations
        # should run a true padded batch on device.  ``speakers`` carries
        # optional per-sentence speaker ids and ``scales`` optional
        # per-sentence synthesis configs (None entries = the model's
        # configured values); implementations must reject non-None entries
        # they cannot honor.
        ...

    def speak_one_sentence(self, phonemes: str) -> "Audio":  # core/src/lib.rs:86
        ...

    # -- type-erased synthesis config (core/src/lib.rs:88-90) --
    def get_fallback_synthesis_config(self) -> Any:
        ...

    def set_fallback_synthesis_config(self, config: Any) -> None:
        ...

    # -- optional capability surface; defaults below --
    def get_default_synthesis_config(self) -> Any:
        ...

    def get_language(self) -> Optional[str]:
        ...

    def get_speakers(self) -> Optional[dict[int, str]]:
        ...

    def properties(self) -> dict[str, str]:
        ...

    def supports_streaming_output(self) -> bool:
        ...

    def stream_synthesis(
        self, phonemes: str, chunk_size: int, chunk_padding: int,
        deadline=None,
    ) -> Iterator["Audio"]:
        ...


class BaseModel:
    """Default implementations for the optional parts of :class:`Model`.

    Mirrors the trait's provided methods: speaker-map helpers
    (``core/src/lib.rs:95-113``), ``properties`` (``:114``), streaming flag +
    default-error ``stream_synthesis`` (``:118-130``).
    """

    def get_language(self) -> Optional[str]:
        return None

    def get_speakers(self) -> Optional[dict[int, str]]:
        return None

    def speaker_id_to_name(self, sid: int) -> Optional[str]:
        speakers = self.get_speakers()
        return speakers.get(sid) if speakers else None

    def speaker_name_to_id(self, name: str) -> Optional[int]:
        speakers = self.get_speakers()
        if not speakers:
            return None
        for sid, sname in speakers.items():
            if sname == name:
                return sid
        return None

    def properties(self) -> dict[str, str]:
        return {}

    def supports_streaming_output(self) -> bool:
        return False

    def stream_synthesis(
        self, phonemes: str, chunk_size: int, chunk_padding: int,
        deadline=None,
    ) -> Iterator["Audio"]:
        raise OperationError(
            "this model does not support streaming synthesis"
        )  # parity: core/src/lib.rs:124-129 default-error impl

    def close(self) -> None:
        """Release model-owned resources (threads, device buffers).

        Counterpart of the reference's voice unload
        (``capi/src/lib.rs:228``); default is a no-op for models without
        background machinery.  Idempotent."""
