"""Backend-adaptive dispatch policy: probe-driven coalescing defaults.

The stream coalescers and the continuous-batching scheduler were designed
for the TPU MXU, where a batched dispatch costs roughly the same wall
time as batch 1 — so funneling N concurrent requests into ONE padded
device program converts contention into throughput.  On a host-CPU
backend the same architecture *loses*: XLA:CPU executes the batch rows
essentially serially, the canonical-batch padding (b ∈ {1, max}) is real
compute, and the gather window is pure added latency.  The repo's own
committed artifact (``BENCH_STREAMING_CPU_r05.json``) measured the
default coalescing config at 2.6x the TTFB of coalescing-off under 8
concurrent CPU streams (33.7 s vs 13.0 s) and 0.66 vs 0.98 audio-s/s.

This module makes the framework act on its own measurements instead of
hard-coded constants (the Orca/vLLM adaptive-batching lineage, PAPERS.md
"continuous batching"):

- :func:`probe_dispatch_scaling` — a one-time, process-cached probe per
  (backend, voice-shape): time a tiny jitted decode-like program at
  batch 1 vs batch N (compiles excluded) and split the cost into
  per-dispatch overhead vs per-item scaling.
- :func:`resolve_policy` — derive concrete knobs for both stream
  coalescers (``models/piper.py``), the :class:`~sonata_tpu.synth.
  scheduler.BatchScheduler`, and the canonical stream batch bucket
  (:mod:`.buckets`).  Fast path: ``jax.default_backend() == "cpu"`` →
  per-request dispatch, the reference's thread-per-stream serving shape
  (``grpc/src/main.rs:381-409``), with no probe paid.  TPU/GPU → the
  tuned coalescing defaults, with the probe refining the gather windows
  (a slow host link stretches per-dispatch overhead, so waiting longer
  to gather a fuller batch is cheap relative to the dispatch itself).

Env overrides always win over the probe (A/B work must stay possible):

- ``SONATA_STREAM_COALESCE=0|1`` (legacy knob, highest precedence;
  honored only when explicitly set): 0 → per-request dispatch, 1 →
  force the coalescing defaults.
- ``SONATA_DISPATCH_POLICY=auto|on|off``: ``on``/``off`` force the
  corresponding shape; ``auto`` (default) applies the backend fast path
  + probe.

``SONATA_DONATE=0|1`` gates buffer donation the same backend-adaptive
way (see :func:`should_donate`).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Optional

from .buckets import canonical_dispatch_batch

log = logging.getLogger("sonata.dispatch")

#: Tuned accelerator defaults — the exact constants the coalescers and
#: scheduler shipped with before the policy existed; unit-test-pinned so
#: the TPU serving shape cannot drift when the policy code changes.
COALESCING_DEFAULTS = {
    "stream_decode_max_batch": 8,
    "stream_decode_max_wait_ms": 2.0,
    "stream_stage_max_batch": 8,
    "stream_stage_max_wait_ms": 8.0,
    "scheduler_max_batch": 16,
    "scheduler_max_wait_ms": 5.0,
}

#: Below this measured parallel speedup at the probe batch, batching N
#: items into one dispatch costs about what N serial dispatches cost —
#: coalescing then buys nothing and its padding/gather-window overhead
#: makes it a net loss (the r05 CPU measurement).
MIN_BATCH_SPEEDUP = 1.5


@dataclass(frozen=True)
class ProbeResult:
    """One dispatch-scaling measurement on a backend.

    ``t1_ms``/``tn_ms``: best-of-reps wall time of the probe program at
    batch 1 and batch ``n``.  The linear split ``t(b) ≈ per_dispatch_ms
    + b * per_item_ms`` is what the policy consumes: ``batch_speedup =
    n * t1 / tn`` is the parallel efficiency of batching (n on an ideal
    MXU, →1.0 on a serial backend).
    """

    backend: str
    n: int
    t1_ms: float
    tn_ms: float

    @property
    def per_item_ms(self) -> float:
        return max((self.tn_ms - self.t1_ms) / max(self.n - 1, 1), 0.0)

    @property
    def per_dispatch_ms(self) -> float:
        return max(self.t1_ms - self.per_item_ms, 0.0)

    @property
    def batch_speedup(self) -> float:
        return self.n * self.t1_ms / max(self.tn_ms, 1e-9)

    def as_dict(self) -> dict:
        d = asdict(self)
        d.update(per_item_ms=round(self.per_item_ms, 4),
                 per_dispatch_ms=round(self.per_dispatch_ms, 4),
                 batch_speedup=round(self.batch_speedup, 3))
        return d


@dataclass(frozen=True)
class DispatchPolicy:
    """Concrete dispatch knobs for one (backend, voice-shape).

    ``coalesce`` is the headline decision; the per-subsystem knobs are
    what :class:`~sonata_tpu.models.piper.PiperVoice`, the stream
    coalescers, and the batch scheduler actually consume.  ``source``
    records *why* (env override / backend fast path / probe) so the
    decision is visible in logs and bench artifacts.
    """

    backend: str
    coalesce: bool
    source: str
    stream_decode_max_batch: int = 8
    stream_decode_max_wait_ms: float = 2.0
    stream_stage_max_batch: int = 8
    stream_stage_max_wait_ms: float = 8.0
    scheduler_max_batch: int = 16
    scheduler_max_wait_ms: float = 5.0
    probe: Optional[ProbeResult] = field(default=None, compare=False)

    # -- consumer views --------------------------------------------------
    def stream_decode_kwargs(self) -> dict:
        return {"max_batch": self.stream_decode_max_batch,
                "max_wait_ms": self.stream_decode_max_wait_ms}

    def stream_stage_kwargs(self) -> dict:
        return {"max_batch": self.stream_stage_max_batch,
                "max_wait_ms": self.stream_stage_max_wait_ms}

    def scheduler_kwargs(self) -> dict:
        return {"max_batch": self.scheduler_max_batch,
                "max_wait_ms": self.scheduler_max_wait_ms}

    def as_dict(self) -> dict:
        """Observability view (logs, bench artifacts)."""
        d = asdict(self)
        d["probe"] = self.probe.as_dict() if self.probe else None
        return d

    def describe(self) -> str:
        """One log line: the decision and where it came from."""
        return (f"dispatch policy [{self.backend}]: "
                f"coalesce={'on' if self.coalesce else 'off'} "
                f"(decode b{self.stream_decode_max_batch}/"
                f"{self.stream_decode_max_wait_ms:g}ms, "
                f"stage b{self.stream_stage_max_batch}/"
                f"{self.stream_stage_max_wait_ms:g}ms, "
                f"sched b{self.scheduler_max_batch}/"
                f"{self.scheduler_max_wait_ms:g}ms) via {self.source}")


# ---------------------------------------------------------------------------
# probe
# ---------------------------------------------------------------------------

_PROBE_CACHE: dict = {}
_PROBE_LOCK = threading.Lock()


def _default_backend() -> str:
    import jax

    return jax.default_backend()


def _time_best(fn, args, reps: int) -> float:
    """Best-of-``reps`` blocking wall time of one jitted call, ms."""
    import jax

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1000.0


def probe_dispatch_scaling(shape_key: tuple = (), *, n: int = 8,
                           reps: int = 5,
                           backend: Optional[str] = None) -> ProbeResult:
    """Measure per-dispatch overhead vs per-item scaling, once per
    (backend, voice-shape, n); later calls return the cached result.

    The probe program is a tiny decode-shaped stack (a few matmul+tanh
    layers over [b, T, C]) — small enough that two XLA compiles cost well
    under a second on a 1-core host, large enough that a backend that
    parallelizes the batch dimension shows it.  ``shape_key``'s first
    element (the voice's latent channel count) sizes the probe's channel
    dimension, bounded, so distinct voice shapes measure distinct
    programs rather than caching N copies of one measurement.  Compiles
    and warmup are excluded from the timing; best-of-``reps`` suppresses
    scheduler noise on loaded hosts.
    """
    backend = backend or _default_backend()
    key = (backend, tuple(shape_key), n)
    with _PROBE_LOCK:
        cached = _PROBE_CACHE.get(key)
    if cached is not None:
        return cached

    import jax
    import jax.numpy as jnp

    T = 32
    C = 64
    if shape_key and isinstance(shape_key[0], int):
        C = max(16, min(int(shape_key[0]), 512))

    @jax.jit
    def tick(x, w):
        for _ in range(4):
            x = jnp.tanh(x @ w)
        return x

    w = jnp.eye(C, dtype=jnp.float32) * 0.5
    x1 = jnp.ones((1, T, C), jnp.float32)
    xn = jnp.ones((n, T, C), jnp.float32)
    # warm both shapes (compile + first-run allocation excluded)
    jax.block_until_ready(tick(x1, w))
    jax.block_until_ready(tick(xn, w))
    result = ProbeResult(backend=backend, n=n,
                         t1_ms=_time_best(tick, (x1, w), reps),
                         tn_ms=_time_best(tick, (xn, w), reps))
    with _PROBE_LOCK:
        # first writer wins; a concurrent duplicate probe is harmless
        cached = _PROBE_CACHE.setdefault(key, result)
    log.debug("dispatch probe %s: t1=%.3fms tn=%.3fms speedup=%.2fx",
              key, cached.t1_ms, cached.tn_ms, cached.batch_speedup)
    return cached


def _clear_probe_cache() -> None:
    """Test hook."""
    with _PROBE_LOCK:
        _PROBE_CACHE.clear()


# ---------------------------------------------------------------------------
# policy resolution
# ---------------------------------------------------------------------------

def _per_request_policy(backend: str, source: str,
                        probe: Optional[ProbeResult] = None
                        ) -> DispatchPolicy:
    """The reference's thread-per-stream shape (grpc/src/main.rs:381-409):
    batch 1, zero gather window, scheduler pass-through."""
    return DispatchPolicy(
        backend=backend, coalesce=False, source=source, probe=probe,
        stream_decode_max_batch=1, stream_decode_max_wait_ms=0.0,
        stream_stage_max_batch=1, stream_stage_max_wait_ms=0.0,
        scheduler_max_batch=1, scheduler_max_wait_ms=0.0)


def _clamp(x: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, x))


def _coalescing_policy(backend: str, source: str,
                       probe: Optional[ProbeResult] = None
                       ) -> DispatchPolicy:
    """The accelerator defaults; with a probe, the gather windows scale
    with measured per-dispatch overhead (a dispatch over a slow tunnel
    costs tens of ms — waiting a little longer to gather a fuller batch
    is then nearly free), floored at the pinned defaults so a fast local
    chip keeps the exact shipped constants."""
    d = dict(COALESCING_DEFAULTS)
    if probe is not None:
        ovh = probe.per_dispatch_ms
        d["stream_decode_max_wait_ms"] = _clamp(
            2.0 * ovh, d["stream_decode_max_wait_ms"], 10.0)
        d["stream_stage_max_wait_ms"] = _clamp(
            4.0 * ovh, d["stream_stage_max_wait_ms"], 25.0)
        d["scheduler_max_wait_ms"] = _clamp(
            2.0 * ovh, d["scheduler_max_wait_ms"], 15.0)
    # canonical-batch rule: the coalescers pad every multi-request group
    # to ONE batch size, which must be a compiled batch bucket so prewarm
    # and dispatch agree on the executable set
    for k in ("stream_decode_max_batch", "stream_stage_max_batch",
              "scheduler_max_batch"):
        d[k] = canonical_dispatch_batch(int(d[k]))
    return DispatchPolicy(backend=backend, coalesce=True, source=source,
                          probe=probe, **d)


def resolve_policy(shape_key: tuple = (), *,
                   backend: Optional[str] = None,
                   env: Optional[dict] = None,
                   probe_fn: Optional[Callable[..., ProbeResult]] = None
                   ) -> DispatchPolicy:
    """Resolve the dispatch policy for one voice.

    Precedence (each layer wins over everything below it):

    1. ``SONATA_STREAM_COALESCE`` **explicitly set** — the legacy A/B
       knob: ``0`` → per-request dispatch, anything else → coalescing
       defaults.  (Unset means "no opinion"; before the policy existed,
       unset silently meant "on".)
    2. ``SONATA_DISPATCH_POLICY=on|off`` — forced shape, no probe.
    3. ``auto`` (default): backend fast path — CPU serves per-request
       without paying a probe; other backends run the cached
       :func:`probe_dispatch_scaling` and keep coalescing only if the
       measured batch speedup clears :data:`MIN_BATCH_SPEEDUP`.

    ``backend``, ``env`` and ``probe_fn`` exist for tests (mocked
    devices, counted probes); production callers pass nothing.
    """
    env = os.environ if env is None else env
    backend = backend or _default_backend()
    probe_fn = probe_fn or probe_dispatch_scaling

    legacy = env.get("SONATA_STREAM_COALESCE")
    if legacy is not None:
        if legacy == "0":
            return _per_request_policy(
                backend, "env:SONATA_STREAM_COALESCE=0")
        return _coalescing_policy(
            backend, f"env:SONATA_STREAM_COALESCE={legacy}")

    mode = env.get("SONATA_DISPATCH_POLICY", "auto").lower()
    if mode not in ("auto", "on", "off"):
        log.warning("invalid SONATA_DISPATCH_POLICY=%r (use auto|on|off); "
                    "falling back to auto", mode)
        mode = "auto"
    if mode == "on":
        return _coalescing_policy(backend, "env:SONATA_DISPATCH_POLICY=on")
    if mode == "off":
        return _per_request_policy(backend, "env:SONATA_DISPATCH_POLICY=off")

    # -- auto ------------------------------------------------------------
    if backend == "cpu":
        # fast path: no probe.  XLA:CPU runs batch rows ~serially, so the
        # coalescers' padding + gather window are pure overhead — measured
        # 2.6x TTFB loss at 8 streams (BENCH_STREAMING_CPU_r05.json).
        return _per_request_policy(backend, "auto:cpu-backend")
    try:
        probe = probe_fn(shape_key, backend=backend)
    except Exception as e:  # a broken probe must never block serving
        log.warning("dispatch probe failed (%s); keeping coalescing "
                    "defaults", e)
        return _coalescing_policy(backend, "auto:probe-failed")
    if probe.batch_speedup < MIN_BATCH_SPEEDUP:
        return _per_request_policy(
            backend, f"auto:probe-speedup-{probe.batch_speedup:.2f}x",
            probe=probe)
    return _coalescing_policy(
        backend, f"auto:probe-speedup-{probe.batch_speedup:.2f}x",
        probe=probe)


# ---------------------------------------------------------------------------
# buffer donation gating
# ---------------------------------------------------------------------------

def should_donate() -> bool:
    """Whether jitted dispatch paths should mark donatable buffers.

    Default: off everywhere.  Investigation of the r05 streaming-bench
    warning ("Some donated buffers were not usable: float32[8,128,192]")
    showed the donated stacked-windows buffer can never alias the decode
    output — XLA input/output aliasing requires identical byte size, and
    [B, width, C] f32 ≠ [B, width*hop] f32 for every voice shape — so
    the annotation was a per-compile warning with zero effect on any
    backend.  ``SONATA_DONATE=1`` re-enables it for A/B measurement
    (``tools/bench_cpu.py`` donation config); ``0`` forces it off.
    """
    setting = os.environ.get("SONATA_DONATE")
    if setting is not None:
        return setting != "0"
    return False
