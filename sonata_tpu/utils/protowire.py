"""Minimal protobuf wire-format codec.

The environment has grpcio but no protoc Python plugin or ``onnx``/
``protobuf`` runtime, so the framework carries its own ~200-line wire codec:
enough of proto3 (varint / 64-bit / length-delimited / 32-bit fields,
packed repeats, maps-as-entry-messages) for the gRPC message surface
(:mod:`sonata_tpu.frontends.grpc_messages`) and the ONNX weight reader
(:mod:`sonata_tpu.models.import_onnx`).

Declarative usage::

    class Version(Message):
        FIELDS = {"version": Field(1, "string")}

    data = Version(version="1.0").encode()
    msg  = Version.decode(data)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, Iterator, Optional

WIRE_VARINT = 0
WIRE_64BIT = 1
WIRE_LEN = 2
WIRE_32BIT = 5

_KIND_WIRE = {
    "string": WIRE_LEN, "bytes": WIRE_LEN, "message": WIRE_LEN,
    "map_int64_string": WIRE_LEN,
    "uint32": WIRE_VARINT, "uint64": WIRE_VARINT, "int64": WIRE_VARINT,
    "int32": WIRE_VARINT, "bool": WIRE_VARINT, "enum": WIRE_VARINT,
    "float": WIRE_32BIT, "double": WIRE_64BIT,
}


class WireError(ValueError):
    pass


def read_varint(buf, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise WireError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise WireError("malformed varint")


def write_varint(n: int) -> bytes:
    # Two's-complement 64-bit mask: negative ints (int64 map keys, enums)
    # must encode as their 10-byte varint form, and an unmasked negative
    # Python int never reaches 0 under >>= 7.
    n &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def iter_fields(buf) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, raw value) over a message buffer.

    Length-delimited values are yielded as zero-copy memoryview slices —
    important for the ONNX reader, where a voice file is ~100 MB and copies
    per tensor would spike memory at load.
    """
    pos = 0
    mv = memoryview(buf)
    while pos < len(mv):
        key, pos = read_varint(mv, pos)
        field, wire = key >> 3, key & 0x7
        if wire == WIRE_VARINT:
            value, pos = read_varint(mv, pos)
        elif wire == WIRE_64BIT:
            if pos + 8 > len(mv):
                raise WireError("truncated 64-bit field")
            value = mv[pos:pos + 8]
            pos += 8
        elif wire == WIRE_LEN:
            n, pos = read_varint(mv, pos)
            if pos + n > len(mv):
                raise WireError("truncated length-delimited field")
            value = mv[pos:pos + n]
            pos += n
        elif wire == WIRE_32BIT:
            if pos + 4 > len(mv):
                raise WireError("truncated 32-bit field")
            value = mv[pos:pos + 4]
            pos += 4
        else:
            raise WireError(f"unsupported wire type {wire}")
        yield field, wire, value


def _encode_value(num: int, kind: str, value, submsg) -> bytes:
    key = write_varint((num << 3) | _KIND_WIRE[kind])
    if kind == "string":
        payload = value.encode("utf-8")
        return key + write_varint(len(payload)) + payload
    if kind == "bytes":
        return key + write_varint(len(value)) + value
    if kind == "message":
        payload = value.encode()
        return key + write_varint(len(payload)) + payload
    if kind in ("uint32", "uint64", "int64", "int32", "enum"):
        return key + write_varint(int(value) & 0xFFFFFFFFFFFFFFFF)
    if kind == "bool":
        return key + write_varint(1 if value else 0)
    if kind == "float":
        return key + struct.pack("<f", float(value))
    if kind == "double":
        return key + struct.pack("<d", float(value))
    if kind == "map_int64_string":
        out = b""
        for k, v in value.items():
            entry = (write_varint((1 << 3) | WIRE_VARINT) + write_varint(int(k))
                     + write_varint((2 << 3) | WIRE_LEN)
                     + write_varint(len(v.encode())) + v.encode())
            out += key + write_varint(len(entry)) + entry
        return out
    raise WireError(f"unknown kind {kind}")


def _decode_value(kind: str, wire: int, raw, submsg):
    if kind == "string":
        return bytes(raw).decode("utf-8", errors="replace")
    if kind == "bytes":
        return bytes(raw)
    if kind == "message":
        return submsg.decode(raw)
    if kind in ("int64", "int32"):
        value = int(raw)
        # proto varints are two's-complement 64-bit: sign-extend negatives
        return value - (1 << 64) if value >= (1 << 63) else value
    if kind in ("uint32", "uint64", "enum"):
        return int(raw)
    if kind == "bool":
        return bool(raw)
    if kind == "float":
        return struct.unpack("<f", raw)[0]
    if kind == "double":
        return struct.unpack("<d", raw)[0]
    if kind == "map_int64_string":
        k = v = None
        for f, w, val in iter_fields(raw):
            if f == 1 and w == WIRE_VARINT:
                k = int(val)
                if k >= (1 << 63):
                    k -= 1 << 64
            elif f == 2 and w == WIRE_LEN:
                v = bytes(val).decode("utf-8", errors="replace")
        return (k, v)
    raise WireError(f"unknown kind {kind}")


def _decode_packed(kind: str, raw, submsg) -> list:
    """Decode a packed repeated scalar payload."""
    out = []
    expected = _KIND_WIRE[kind]
    if expected == WIRE_VARINT:
        mv = memoryview(raw)
        pos = 0
        while pos < len(mv):
            v, pos = read_varint(mv, pos)
            out.append(_decode_value(kind, WIRE_VARINT, v, submsg))
    else:
        width = 4 if expected == WIRE_32BIT else 8
        mv = memoryview(raw)
        if len(mv) % width:
            raise WireError("truncated packed fixed-width payload")
        for i in range(0, len(mv), width):
            out.append(_decode_value(kind, expected, mv[i:i + width],
                                     submsg))
    return out


@dataclass(frozen=True)
class Field:
    num: int
    kind: str
    message: Optional[type] = None  # for kind == "message"
    repeated: bool = False


class Message:
    """Base for declarative wire messages: subclass and define ``FIELDS``."""

    FIELDS: dict[str, Field] = {}

    def __init__(self, **kwargs):
        for name in self.FIELDS:
            f = self.FIELDS[name]
            default = [] if f.repeated else ({} if f.kind ==
                                             "map_int64_string" else None)
            setattr(self, name, kwargs.pop(name, default))
        if kwargs:
            raise TypeError(f"unknown fields: {sorted(kwargs)}")

    def encode(self) -> bytes:
        out = b""
        for name, f in self.FIELDS.items():
            value = getattr(self, name)
            if value is None:
                continue
            if f.repeated:
                for item in value:
                    out += _encode_value(f.num, f.kind, item, f.message)
            elif f.kind == "map_int64_string":
                if value:
                    out += _encode_value(f.num, f.kind, value, f.message)
            else:
                out += _encode_value(f.num, f.kind, value, f.message)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        by_num = {f.num: (name, f) for name, f in cls.FIELDS.items()}
        msg = cls()
        for num, wire, raw in iter_fields(data):
            entry = by_num.get(num)
            if entry is None:
                continue  # unknown field: skip (proto3 semantics)
            name, f = entry
            expected = _KIND_WIRE[f.kind]
            if wire != expected:
                if (f.repeated and wire == WIRE_LEN
                        and expected in (WIRE_VARINT, WIRE_32BIT,
                                         WIRE_64BIT)):
                    # packed repeated scalars (proto3 writers pack by
                    # default)
                    getattr(msg, name).extend(
                        _decode_packed(f.kind, raw, f.message))
                # else: wire-type mismatch (malformed or incompatible
                # writer) — treat like an unknown field, don't crash mid-RPC
                continue
            value = _decode_value(f.kind, wire, raw, f.message)
            if f.repeated:
                getattr(msg, name).append(value)
            elif f.kind == "map_int64_string":
                k, v = value
                getattr(msg, name)[k] = v
            else:
                setattr(msg, name, value)
        return msg

    def __repr__(self):
        fields = ", ".join(f"{n}={getattr(self, n)!r}" for n in self.FIELDS
                           if getattr(self, n) not in (None, [], {}))
        return f"{type(self).__name__}({fields})"

    def __eq__(self, other):
        return (type(self) is type(other)
                and all(getattr(self, n) == getattr(other, n)
                        for n in self.FIELDS))
