from .buckets import BATCH_BUCKETS, FRAME_BUCKETS, TEXT_BUCKETS, bucket_for, pad_to

__all__ = ["BATCH_BUCKETS", "FRAME_BUCKETS", "TEXT_BUCKETS", "bucket_for",
           "pad_to"]
