from .buckets import (
    BATCH_BUCKETS,
    FRAME_BUCKETS,
    TEXT_BUCKETS,
    bucket_for,
    canonical_dispatch_batch,
    pad_to,
)
from .dispatch_policy import (
    DispatchPolicy,
    ProbeResult,
    probe_dispatch_scaling,
    resolve_policy,
)

__all__ = ["BATCH_BUCKETS", "FRAME_BUCKETS", "TEXT_BUCKETS", "bucket_for",
           "canonical_dispatch_batch", "pad_to", "DispatchPolicy",
           "ProbeResult", "probe_dispatch_scaling", "resolve_policy"]
