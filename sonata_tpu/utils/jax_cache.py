"""Persistent XLA compile cache setup, shared by every long-lived entry
point (gRPC server, CLI, benches).

The reference pays no compilation cost — ONNX Runtime sessions load in
milliseconds (``crates/sonata/models/piper/src/lib.rs:342-399``).  Here the
first compile of a full-pipeline shape costs tens of seconds on a remote
chip, so anything that boots repeatedly must reuse compiled executables
across processes: with the cache enabled, a re-boot loads each shape from
disk in well under a second instead of re-invoking XLA.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> str | None:
    """Honor ``SONATA_PLATFORM`` (cpu / tpu / …) via ``jax.config``.

    Plain ``JAX_PLATFORMS`` is read at first-jax-import time; in
    environments where a sitecustomize (or any earlier import) has
    already pulled jax in, the env var is silently too late and the
    process can hang probing an unreachable accelerator plugin.  The
    config API works at any point before first backend use, so the CLI
    and gRPC entry points call this first.  Returns the pinned platform
    or None.
    """
    platform = os.environ.get("SONATA_PLATFORM")
    if not platform:
        return None
    import jax

    jax.config.update("jax_platforms", platform)
    return platform


AOT_CACHE_ENV = "SONATA_AOT_CACHE"


def _default_cache_dir() -> str:
    """``SONATA_JAX_CACHE_DIR`` > ``$XDG_CACHE_HOME/sonata_jax`` >
    ``~/.cache/sonata_jax`` (one resolution for both cache layers)."""
    return os.environ.get("SONATA_JAX_CACHE_DIR") or os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "sonata_jax")


def enable_persistent_compile_cache(min_compile_secs: float = 1.0) -> str | None:
    """Point JAX's compilation cache at a per-user directory and return it.

    Directory resolution: ``SONATA_JAX_CACHE_DIR``, else
    ``$XDG_CACHE_HOME/sonata_jax``, else ``~/.cache/sonata_jax``.  The
    directory is created mode 0700 — a world-writable location (e.g. a
    predictable /tmp name) could be pre-created and poisoned by another
    local user.  Returns None (and changes nothing) on any failure: the
    cache is an optimization, never a boot blocker.
    """
    try:
        import jax

        cache_dir = _default_cache_dir()
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        return cache_dir
    except Exception:
        return None


def aot_cache_dir() -> str | None:
    """Directory for serialized AOT executables (the warmup lattice's
    fast-boot layer), or None when disabled/unavailable.

    JAX's own persistent cache skips the XLA compile on a cache hit but
    still re-traces and re-lowers every jitted shape — ~1-2 s per
    full-pipeline shape, paid again on EVERY boot.  The AOT layer
    serializes the *compiled executable* itself
    (``jax.experimental.serialize_executable``), so the next boot loads
    each shape in ~0.3 s with zero retracing.  ``SONATA_AOT_CACHE``:
    ``0``/``off`` disables, a path overrides, unset defaults to
    ``<jax cache dir>/aot``.  Created mode 0700 — the blobs are
    pickles and the directory must be trusted like the XLA cache it
    sits inside.  Returns None on any failure: an optimization, never
    a boot blocker.
    """
    raw = (os.environ.get(AOT_CACHE_ENV) or "").strip()
    if raw.lower() in ("0", "off", "false", "no"):
        return None
    try:
        aot_dir = raw or os.path.join(_default_cache_dir(), "aot")
        os.makedirs(aot_dir, mode=0o700, exist_ok=True)
        return aot_dir
    except Exception:
        return None
