"""Observability: RTF counters and profiler trace capture.

The reference's entire tracing story is a wall-clock around each ORT run
surfaced as ``real_time_factor`` (SURVEY §5).  We keep that (every
``Audio`` carries ``inference_ms``) and add the TPU-native pieces the
survey calls for: aggregate RTF counters and ``jax.profiler`` trace
capture for Tensorboard/XProf.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class RtfStats:
    utterances: int = 0
    audio_ms: float = 0.0
    inference_ms: float = 0.0

    @property
    def rtf(self) -> float:
        return self.inference_ms / self.audio_ms if self.audio_ms else 0.0

    @property
    def audio_seconds_per_second(self) -> float:
        return 1.0 / self.rtf if self.rtf else 0.0


class RtfCounter:
    """Thread-safe aggregate RTF accounting (e.g. one per gRPC server)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats = RtfStats()

    def record(self, audio) -> None:
        """Record one synthesized :class:`~sonata_tpu.audio.Audio`."""
        with self._lock:
            self._stats.utterances += 1
            self._stats.audio_ms += audio.duration_ms()
            self._stats.inference_ms += audio.inference_ms

    def snapshot(self) -> RtfStats:
        with self._lock:
            return RtfStats(self._stats.utterances, self._stats.audio_ms,
                            self._stats.inference_ms)

    def reset(self) -> None:
        with self._lock:
            self._stats = RtfStats()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``log_dir`` (view with
    Tensorboard/XProf)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def timed(label: str, sink: Optional[list] = None) -> Iterator[None]:
    """Wall-clock a block; append ``(label, seconds)`` to ``sink`` or log."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if sink is not None:
            sink.append((label, dt))
        else:
            import logging

            logging.getLogger("sonata.profiling").debug(
                "%s: %.1f ms", label, dt * 1e3)
