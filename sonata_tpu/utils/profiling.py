"""Observability: RTF counters and profiler trace capture.

The reference's entire tracing story is a wall-clock around each ORT run
surfaced as ``real_time_factor`` (SURVEY §5).  We keep that (every
``Audio`` carries ``inference_ms``) and add the TPU-native pieces the
survey calls for: aggregate RTF counters and ``jax.profiler`` trace
capture for Tensorboard/XProf.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class RtfStats:
    utterances: int = 0
    audio_ms: float = 0.0
    inference_ms: float = 0.0

    @property
    def rtf(self) -> float:
        return self.inference_ms / self.audio_ms if self.audio_ms else 0.0

    @property
    def audio_seconds_per_second(self) -> float:
        return 1.0 / self.rtf if self.rtf else 0.0


class RtfCounter:
    """Thread-safe aggregate RTF accounting (e.g. one per gRPC server)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stats = RtfStats()

    def record(self, audio) -> None:
        """Record one synthesized :class:`~sonata_tpu.audio.Audio`."""
        with self._lock:
            self._stats.utterances += 1
            self._stats.audio_ms += audio.duration_ms()
            self._stats.inference_ms += audio.inference_ms

    def snapshot(self) -> RtfStats:
        with self._lock:
            return RtfStats(self._stats.utterances, self._stats.audio_ms,
                            self._stats.inference_ms)

    def reset(self) -> None:
        with self._lock:
            self._stats = RtfStats()


#: Default latency buckets (seconds): 5 ms .. 30 s, roughly 2.5x apart.
#: Spans a TTFB on a warm accelerator (~tens of ms) through a cold-compile
#: first request (tens of seconds); everything beyond lands in +Inf.
DEFAULT_LATENCY_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                             1.0, 2.5, 5.0, 10.0, 30.0)

#: Queue-wait buckets (seconds): a request's time in the batch scheduler
#: queue is normally sub-millisecond (the gather window) but stretches to
#: seconds when the voice is backed up — the low end needs resolution the
#: latency buckets don't have.
QUEUE_WAIT_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                        0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


@dataclass
class HistogramSnapshot:
    """Point-in-time copy of a :class:`Histogram` (cumulative counts)."""

    buckets: tuple  # upper bounds, seconds (excluding +Inf)
    counts: tuple   # cumulative count per bound, then the +Inf total last
    total: int
    sum: float


class Histogram:
    """Thread-safe bounded-bucket histogram (Prometheus-style cumulative).

    Fixed bucket bounds chosen at construction keep memory constant no
    matter how many observations arrive — the property that makes it safe
    as an always-on serving metric (vs. recording raw samples).
    """

    def __init__(self, buckets=None):
        bounds = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS_S))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    @property
    def bounds(self) -> tuple:
        return self._bounds

    def observe(self, value: float) -> None:
        # linear scan: bucket lists are ~a dozen entries, and the scan is
        # cheaper than bisect's function-call overhead at this size
        idx = len(self._bounds)
        for i, b in enumerate(self._bounds):
            if value <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._total += 1

    def snapshot(self) -> HistogramSnapshot:
        with self._lock:
            counts = list(self._counts)
            total, s = self._total, self._sum
        # cumulative counts, Prometheus exposition semantics
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return HistogramSnapshot(buckets=self._bounds, counts=tuple(cum),
                                 total=total, sum=s)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``log_dir`` (view with
    Tensorboard/XProf)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


#: the jax profiler cannot nest captures; serialize /debug/profile hits
_PROFILE_LOCK = threading.Lock()


def capture_profile(seconds: float, log_dir: Optional[str] = None) -> str:
    """Capture a ``jax.profiler`` device trace for ``seconds`` and return
    the log directory (view with Tensorboard/XProf or Perfetto).

    What the metrics plane's ``/debug/profile?seconds=`` endpoint runs:
    the tracing layer answers *where a request's wall time went*; this
    answers *what the device was doing meanwhile*.  Raises
    ``RuntimeError`` when a capture is already running (the profiler
    cannot nest).
    """
    import tempfile

    seconds = min(max(float(seconds), 0.1), 60.0)
    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="sonata_profile_")
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    try:
        with trace(log_dir):
            time.sleep(seconds)
    finally:
        _PROFILE_LOCK.release()
    return log_dir


@contextlib.contextmanager
def timed(label: str, sink: Optional[list] = None) -> Iterator[None]:
    """Wall-clock a block; append ``(label, seconds)`` to ``sink`` or log."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if sink is not None:
            sink.append((label, dt))
        else:
            import logging

            logging.getLogger("sonata.profiling").debug(
                "%s: %.1f ms", label, dt * 1e3)
