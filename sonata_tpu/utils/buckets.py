"""Static-shape bucketing.

XLA compiles one executable per shape; the reference leans on ONNX dynamic
shapes instead (``piper/src/lib.rs:346,541``), which do not exist on TPU.
Buckets bound the number of compiles: sequences pad up to the next bucket
and masks carry the true lengths (SURVEY §7 "Dynamic shapes vs XLA").
"""

from __future__ import annotations

TEXT_BUCKETS = (16, 32, 64, 96, 128, 192, 256, 384, 512)
FRAME_BUCKETS = (64, 128, 256, 384, 512, 768, 1024, 1536, 2048, 3072, 4096)
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def bucket_for(n: int, buckets=TEXT_BUCKETS) -> int:
    """Smallest bucket ≥ n; multiples of the largest bucket if beyond."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return ((n + top - 1) // top) * top


def pad_to(seq, length: int, value=0):
    """Pad a python list to ``length``."""
    return list(seq) + [value] * (length - len(seq))


def canonical_dispatch_batch(max_batch: int) -> int:
    """Canonical batch size for a coalesced dispatch group.

    The stream coalescers pad every multi-request group to ONE batch
    size so the compiled-executable set per stage is exactly {1, max} —
    that size must be a :data:`BATCH_BUCKETS` bucket, or prewarm (which
    walks buckets) and dispatch would disagree on the shape set.  Used
    by :mod:`.dispatch_policy` when deriving coalescer knobs.
    """
    return bucket_for(max(int(max_batch), 1), BATCH_BUCKETS)
