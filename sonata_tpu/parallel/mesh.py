"""Device mesh construction and sharding helpers.

The reference's entire parallelism story is host threads (rayon fan-out over
sentences, SURVEY §2.4); its distributed story is "none" (§5).  Here the
equivalent axes are real hardware axes:

- ``data`` — sentence batches sharded across chips over ICI (the TPU
  counterpart of the rayon ``par_iter``),
- ``seq``  — sequence (context) parallelism for long inputs via ring
  attention (:mod:`.ring`).

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize``
so a pod slice forms one mesh; batches ride ICI inside a slice and DCN
across slices (the XLA-collectives replacement for the NCCL/MPI backends a
GPU framework would carry).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("sonata.parallel")

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(n_devices: Optional[int] = None, *,
              seq_parallel: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(data, seq)`` mesh over the first ``n_devices`` devices.

    ``seq_parallel`` splits the device pool between batch parallelism and
    sequence parallelism; 1 means a pure data mesh.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devs)} devices are available")
        devs = devs[:n_devices]
    n = len(devs)
    if n % seq_parallel != 0:
        raise ValueError(
            f"{n} devices not divisible by seq_parallel={seq_parallel}")
    grid = np.array(devs).reshape(n // seq_parallel, seq_parallel)
    return Mesh(grid, (DATA_AXIS, SEQ_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-axis sharding for [B, ...] tensors."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Join a multi-host JAX runtime (no-op when single-process).

    On TPU pods the defaults are discovered from the environment; arguments
    exist for explicit DCN setups.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        log.info("distributed runtime: process %d/%d, %d local devices",
                 jax.process_index(), jax.process_count(),
                 jax.local_device_count())
    except (RuntimeError, ValueError) as e:
        log.debug("distributed init skipped: %s", e)
