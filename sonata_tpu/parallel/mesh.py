"""Device mesh construction and sharding helpers.

The reference's entire parallelism story is host threads (rayon fan-out over
sentences, SURVEY §2.4); its distributed story is "none" (§5).  Here the
equivalent axes are real hardware axes:

- ``data``  — sentence batches sharded across chips over ICI (the TPU
  counterpart of the rayon ``par_iter``),
- ``seq``   — sequence (context) parallelism for long inputs via ring
  attention (:mod:`.ring`),
- ``model`` — tensor parallelism: the HiFi-GAN decoder's channel
  dimension (where the synthesis FLOPs live) shards across chips; the
  conv output-channel annotations below let XLA's SPMD partitioner
  run each upsampling stage as a channel-split matmul on every chip
  and insert the all-reduces only where channels mix back down
  (conv_post).

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize``
so a pod slice forms one mesh; batches ride ICI inside a slice and DCN
across slices (the XLA-collectives replacement for the NCCL/MPI backends a
GPU framework would carry).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("sonata.parallel")

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def make_mesh(n_devices: Optional[int] = None, *,
              seq_parallel: int = 1,
              model_parallel: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(data, seq, model)`` mesh over ``n_devices`` devices.

    ``seq_parallel`` and ``model_parallel`` split the device pool
    between batch, sequence, and tensor parallelism; both default to 1
    (a pure data mesh).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devs)} devices are available")
        devs = devs[:n_devices]
    n = len(devs)
    inner = seq_parallel * model_parallel
    if n % inner != 0:
        raise ValueError(
            f"{n} devices not divisible by seq_parallel={seq_parallel} "
            f"* model_parallel={model_parallel}")
    grid = np.array(devs).reshape(n // inner, seq_parallel,
                                  model_parallel)
    return Mesh(grid, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-axis sharding for [B, ...] tensors."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_shardings(mesh: Mesh, params) -> "object":
    """Per-leaf shardings for the VITS params pytree: tensor-parallel
    decoder, replicated everything else.

    The HiFi-GAN decoder dominates synthesis FLOPs; its conv kernels are
    ``[k, Cin, Cout]`` (transposed-conv ups included) and its biases
    ``[Cout]``.  The annotation follows the Megatron column/row pairing
    where the graph allows it: ``conv_pre``/``ups`` and each resblock's
    ``convs1`` shard their output channels (column), each resblock's
    ``convs2`` shards its input channels (row) so the pair needs one
    partial-sum reduce instead of an activation re-shard per conv.
    Around the residual adds and stage boundaries XLA's SPMD partitioner
    inserts whatever reshard the propagation demands — the collective
    schedule is the compiler's, these annotations only express where the
    channel parallelism lives.  With ``model_parallel == 1`` the result
    is the plain replicated tree.
    """
    import jax.tree_util as jtu

    if mesh.shape.get(MODEL_AXIS, 1) <= 1:
        rep = replicated(mesh)
        return jtu.tree_map(lambda _: rep, params)
    rep = replicated(mesh)
    col = NamedSharding(mesh, P(None, None, MODEL_AXIS))
    row = NamedSharding(mesh, P(None, MODEL_AXIS, None))
    bias = NamedSharding(mesh, P(MODEL_AXIS))
    tp = mesh.shape[MODEL_AXIS]

    def leaf_sharding(path, leaf):
        keys = [getattr(p, "key", getattr(p, "idx", None)) for p in path]
        if "dec" not in keys or "conv_post" in keys:
            return rep
        name = keys[-1]
        ndim = getattr(leaf, "ndim", 0)
        if "convs2" in keys:
            # row-parallel half of the Megatron pair: contract over the
            # sharded Cin that convs1 produced
            if name == "w" and ndim == 3 and leaf.shape[1] % tp == 0:
                return row
            return rep  # bias adds after the reduce: replicated
        if name == "w" and ndim == 3 and leaf.shape[2] % tp == 0:
            return col
        if name == "b" and ndim == 1 and leaf.shape[0] % tp == 0:
            return bias
        return rep

    return jtu.tree_map_with_path(leaf_sharding, params)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Join a multi-host JAX runtime (no-op when single-process).

    On TPU pods the defaults are discovered from the environment; arguments
    exist for explicit DCN setups.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        log.info("distributed runtime: process %d/%d, %d local devices",
                 jax.process_index(), jax.process_count(),
                 jax.local_device_count())
    except (RuntimeError, ValueError) as e:
        log.debug("distributed init skipped: %s", e)
