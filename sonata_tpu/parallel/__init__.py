"""Device-mesh parallelism: data-parallel batch sharding, sequence-parallel
ring attention, multi-host initialization (analogue of — and upgrade over —
the reference's rayon thread fan-out, SURVEY §2.4/§5)."""

from . import checkpoint
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    data_sharding,
    initialize_distributed,
    make_mesh,
    param_shardings,
    replicated,
)
from .ring import ring_attention, ring_attention_sharded

__all__ = [
    "checkpoint",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "data_sharding",
    "initialize_distributed",
    "make_mesh",
    "param_shardings",
    "replicated",
    "ring_attention",
    "ring_attention_sharded",
]
