"""Sharded checkpoint save/restore via Orbax.

The single-voice format is a flat ``.npz``
(:mod:`sonata_tpu.models.serialization`) — right for one host loading one
file.  On a pod, every host re-reading the full archive and re-sharding
wastes startup time and HBM staging; Orbax writes/reads each param shard
from the process that owns it, so multi-host restore is parallel and
arrives already laid out for the mesh.

Usage::

    from sonata_tpu.parallel import make_mesh, checkpoint

    mesh = make_mesh()
    checkpoint.save("/ckpt/voice1", voice.params)
    params = checkpoint.restore("/ckpt/voice1", like=voice.params)
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional, Union

from ..core import FailedToLoadResource


def _checkpointer():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:  # pragma: no cover
        raise FailedToLoadResource(
            "orbax is required for sharded checkpoints") from e
    return ocp


def save(path: Union[str, Path], params: Any, *,
         force: bool = False) -> None:
    """Write a sharded checkpoint of a param pytree.

    ``force=False`` (the default, matching Orbax) refuses to overwrite an
    existing checkpoint; pass ``force=True`` to replace it."""
    ocp = _checkpointer()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(Path(path).resolve(), params, force=force)


def restore(path: Union[str, Path], *, like: Optional[Any] = None) -> Any:
    """Restore a param pytree.

    ``like``: an abstract or concrete pytree (e.g. freshly-initialized
    params, possibly already sharded over a mesh) giving the target
    structure, dtypes, and shardings; restoring without it yields
    host-local arrays.
    """
    ocp = _checkpointer()
    p = Path(path).resolve()
    if not p.exists():
        raise FailedToLoadResource(f"checkpoint not found: {p}")
    try:
        with ocp.StandardCheckpointer() as ckptr:
            if like is not None:
                import jax

                abstract = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(
                        x.shape, x.dtype,
                        sharding=getattr(x, "sharding", None)),
                    like)
                return ckptr.restore(p, abstract)
            return ckptr.restore(p)
    except FailedToLoadResource:
        raise
    except Exception as e:  # corrupt/partial checkpoint: orbax internals
        raise FailedToLoadResource(
            f"cannot restore checkpoint {p}: {e}") from e
