"""Ring attention: exact attention over a sequence-sharded axis.

Long-context support is first-class in this framework (the reference bounds
every graph to one sentence and chunks inside it, SURVEY §5 "long-context";
we must also serve inputs that exceed one chip's memory).  The mechanism is
the standard ring schedule: each device holds a shard of the sequence; K/V
blocks rotate around the ring via ``lax.ppermute`` (XLA lowers this to ICI
neighbor exchanges) while each device accumulates its queries' attention
online (flash-attention style running max/denominator), so the result is
*exact* attention with O(T/n) memory per chip and compute/communication
overlap handled by XLA's async collectives.

Used via ``shard_map`` over the ``seq`` axis of the mesh
(:func:`ring_attention`), or directly inside an spmd region
(:func:`ring_attention_sharded`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .mesh import SEQ_AXIS


def _block_attend(q, k, v, mask, m_prev, l_prev, acc_prev, scale):
    """One K/V block of online-softmax attention.

    q: [B, H, Tq, D]; k/v: [B, H, Tk, D]; mask: [B, 1, Tq, Tk] additive.
    Carries the flash-attention running statistics (m, l, acc).
    """
    logits = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
    if mask is not None:
        logits = logits + mask
    m_cur = jnp.max(logits, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(logits - m_new[..., None])
    l_cur = jnp.sum(p, axis=-1)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + l_cur
    acc_new = acc_prev * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc_new


def ring_attention_sharded(q, k, v, kv_valid, *, axis_name: str = SEQ_AXIS):
    """Exact attention where q/k/v are already sequence-sharded per device.

    Must run inside ``shard_map`` (or any spmd region) over ``axis_name``.

    q, k, v: [B, H, T_local, D] local shards.
    kv_valid: [B, T_local] float/bool — 1 for real positions (padding mask
    travels with its K/V shard around the ring).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    b, h, tq, d = q.shape

    # derive carries from q so they inherit q's varying-axis type under
    # shard_map (a plain jnp.zeros would be axis-invariant and fail the
    # fori_loop carry check on jax >= 0.8)
    m0 = jnp.full_like(q[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q[..., 0])
    acc0 = jnp.zeros_like(q)

    def step(i, carry):
        m, l, acc, k_blk, v_blk, valid_blk = carry
        mask = jnp.where(valid_blk[:, None, None, :] > 0, 0.0, -1e9)
        mask = mask.astype(q.dtype)
        m, l, acc = _block_attend(q, k_blk, v_blk, mask, m, l, acc, scale)
        # rotate K/V (and their validity) one step around the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        valid_blk = lax.ppermute(valid_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk, valid_blk

    m, l, acc, _, _, _ = lax.fori_loop(
        0, n, step, (m0, l0, acc0, k, v, kv_valid.astype(q.dtype)))
    del idx  # ring is rotation-symmetric; no per-device offsets needed
    return acc / jnp.maximum(l[..., None], 1e-9)


def ring_attention(q, k, v, lengths, mesh: Mesh, *,
                   axis_name: str = SEQ_AXIS):
    """Convenience wrapper: shard [B, H, T, D] q/k/v over the mesh's ``seq``
    axis and run :func:`ring_attention_sharded`.

    ``lengths``: [B] true sequence lengths (positions beyond are masked).
    T must be divisible by the size of the seq axis.
    """
    t = q.shape[2]
    positions = jnp.arange(t)[None, :]  # [1, T]
    kv_valid = (positions < lengths[:, None]).astype(q.dtype)  # [B, T]

    spec_qkv = P(None, None, axis_name, None)
    spec_valid = P(None, axis_name)

    fn = shard_map(
        partial(ring_attention_sharded, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_valid),
        out_specs=spec_qkv,
    )
    return fn(q, k, v, kv_valid)
