"""Ring attention: exact attention over a sequence-sharded axis.

Long-context support is first-class in this framework (the reference bounds
every graph to one sentence and chunks inside it, SURVEY §5 "long-context";
we must also serve inputs that exceed one chip's memory).  The mechanism is
the standard ring schedule: each device holds a shard of the sequence; K/V
blocks rotate around the ring via ``lax.ppermute`` (XLA lowers this to ICI
neighbor exchanges) while each device accumulates its queries' attention
online (flash-attention style running max/denominator), so the result is
*exact* attention with O(T/n) memory per chip and compute/communication
overlap handled by XLA's async collectives.

Used via ``shard_map`` over the ``seq`` axis of the mesh
(:func:`ring_attention`), or directly inside an spmd region
(:func:`ring_attention_sharded`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .mesh import SEQ_AXIS


def _axis_size(axis_name: str) -> int:
    """Static size of a mapped axis, across jax versions.

    ``jax.lax.axis_size`` only exists in newer jax; on older releases
    (e.g. 0.4.x, this environment) the static size is reachable via
    ``jax.core.axis_frame``, which returns the size itself as an int
    (newer intermediates return a frame object carrying ``.size``)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax.core import axis_frame

    frame = axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def _block_attend(q, k, v, mask, m_prev, l_prev, acc_prev, scale,
                  extra_v=None):
    """One K/V block of online-softmax attention.

    q: [B, H, Tq, D]; k/v: [B, H, Tk, D]; mask: [B, 1, Tq, Tk] additive
    (also carries any extra logits bias, e.g. relative-position terms).
    ``extra_v``: optional [Tq, Tk, D] per-pair value contribution (the
    relative-value table), accumulated with the same weights.
    Carries the flash-attention running statistics (m, l, acc).
    """
    logits = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
    if mask is not None:
        logits = logits + mask
    m_cur = jnp.max(logits, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(logits - m_new[..., None])
    l_cur = jnp.sum(p, axis=-1)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + l_cur
    upd = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    if extra_v is not None:
        upd = upd + jnp.einsum("bhqk,qkd->bhqd", p, extra_v)
    acc_new = acc_prev * alpha[..., None] + upd
    return m_new, l_new, acc_new


def ring_attention_sharded(q, k, v, kv_valid, *, axis_name: str = SEQ_AXIS):
    """Exact attention where q/k/v are already sequence-sharded per device.

    Must run inside ``shard_map`` (or any spmd region) over ``axis_name``.

    q, k, v: [B, H, T_local, D] local shards.
    kv_valid: [B, T_local] float/bool — 1 for real positions (padding mask
    travels with its K/V shard around the ring).
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    b, h, tq, d = q.shape

    # derive carries from q so they inherit q's varying-axis type under
    # shard_map (a plain jnp.zeros would be axis-invariant and fail the
    # fori_loop carry check on jax >= 0.8)
    m0 = jnp.full_like(q[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q[..., 0])
    acc0 = jnp.zeros_like(q)

    def step(i, carry):
        m, l, acc, k_blk, v_blk, valid_blk = carry
        mask = jnp.where(valid_blk[:, None, None, :] > 0, 0.0, -1e9)
        mask = mask.astype(q.dtype)
        m, l, acc = _block_attend(q, k_blk, v_blk, mask, m, l, acc, scale)
        # rotate K/V (and their validity) one step around the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        valid_blk = lax.ppermute(valid_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk, valid_blk

    m, l, acc, _, _, _ = lax.fori_loop(
        0, n, step, (m0, l0, acc0, k, v, kv_valid.astype(q.dtype)))
    del idx  # ring is rotation-symmetric; no per-device offsets needed
    return acc / jnp.maximum(l[..., None], 1e-9)


def ring_rel_attention_sharded(q, k, v, kv_valid, rel_k, rel_v, *,
                               window: int, axis_name: str = SEQ_AXIS):
    """Ring attention with VITS's learned windowed relative-position
    embeddings (the text encoder's attention flavor,
    :func:`sonata_tpu.models.modules.rel_attention`).

    The relative term touches only positions with ``|s - t| <= window``
    (window=4 in Piper VITS), so on a ring it is nonzero only for the
    local block and its immediate neighbors — the gather below evaluates
    it per rotating block from each block's global offset.

    q, k, v: [B, H, T_local, D] local shards; kv_valid: [B, T_local];
    rel_k, rel_v: [2*window+1, D] (position ``r`` ⇔ offset ``r - window``).
    Must run inside ``shard_map`` over ``axis_name``.
    """
    n = _axis_size(axis_name)  # static: unrolled ring schedule
    idx = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    b, h, t_loc, d = q.shape
    w = window

    # query·rel-key for all 2w+1 offsets, hoisted out of the ring loop
    qrel = jnp.einsum("bhtd,rd->bhtr", q * scale, rel_k)  # [B,H,T,2w+1]
    t_idx = jnp.arange(t_loc)

    m = jnp.full_like(q[..., 0], -jnp.inf)
    l = jnp.zeros_like(q[..., 0])
    acc = jnp.zeros_like(q)
    k_blk, v_blk = k, v
    valid_blk = kv_valid.astype(q.dtype)

    for i in range(n):
        src = (idx - i) % n  # which global block this k/v shard is
        off = (src - idx) * t_loc
        delta = off + (t_idx[None, :] - t_idx[:, None])  # [Tq, Tk] s - t
        in_win = (jnp.abs(delta) <= w)
        ridx = jnp.clip(delta + w, 0, 2 * w)  # [Tq, Tk]

        rel_term = jnp.take_along_axis(
            qrel, jnp.broadcast_to(ridx, (b, h, t_loc, t_loc)), axis=-1)
        bias = (jnp.where(in_win, rel_term, 0.0)
                + jnp.where(valid_blk[:, None, None, :] > 0,
                            0.0, -1e9)).astype(q.dtype)
        # relative-value table gathered per (t, s) pair (zero outside
        # the window)
        rel_v_g = jnp.where(in_win[..., None], rel_v[ridx], 0.0)
        m, l, acc = _block_attend(q, k_blk, v_blk, bias, m, l, acc, scale,
                                  extra_v=rel_v_g)
        if i < n - 1:  # final block needs no rotation
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            valid_blk = lax.ppermute(valid_blk, axis_name, perm)

    return acc / jnp.maximum(l[..., None], 1e-9)


def halo_exchange(x, pad_left: int, pad_right: int, *,
                  axis_name: str = SEQ_AXIS):
    """Extend a sequence-sharded ``[B, T_local, C]`` block with its
    neighbors' boundary columns (zeros at the sequence ends, matching the
    zero padding a conv sees on an unsharded sequence).

    The permutes are non-circular: device 0's left halo and device n-1's
    right halo stay zero (``ppermute`` fills non-received slots with 0).
    """
    n = _axis_size(axis_name)
    parts = []
    if pad_left:
        left = lax.ppermute(x[:, -pad_left:], axis_name,
                            [(j, j + 1) for j in range(n - 1)])
        parts.append(left)
    parts.append(x)
    if pad_right:
        right = lax.ppermute(x[:, :pad_right], axis_name,
                             [(j + 1, j) for j in range(n - 1)])
        parts.append(right)
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else x


def ring_attention(q, k, v, lengths, mesh: Mesh, *,
                   axis_name: str = SEQ_AXIS):
    """Convenience wrapper: shard [B, H, T, D] q/k/v over the mesh's ``seq``
    axis and run :func:`ring_attention_sharded`.

    ``lengths``: [B] true sequence lengths (positions beyond are masked).
    T must be divisible by the size of the seq axis.
    """
    t = q.shape[2]
    positions = jnp.arange(t)[None, :]  # [1, T]
    kv_valid = (positions < lengths[:, None]).astype(q.dtype)  # [B, T]

    spec_qkv = P(None, None, axis_name, None)
    spec_valid = P(None, axis_name)

    fn = shard_map(
        partial(ring_attention_sharded, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_valid),
        out_specs=spec_qkv,
    )
    return fn(q, k, v, kv_valid)
