"""Per-request deadlines and client-disconnect propagation.

The reference server has no deadline story at all: ``SynthesizeUtterance``
blocks on the session run until it finishes, however long that takes
(``grpc/src/main.rs:321-355``), and a client that hangs up leaves the
synthesis running to completion.  Under overload that is how queues grow
without bound — work is still performed for callers that stopped waiting
for it.

A :class:`Deadline` travels with a request from the frontend into the
batch scheduler.  It answers two questions any stage can ask cheaply:

- *has this request run out of time?* (``expired()``) — derived from the
  gRPC context deadline when the client set one, else from the server
  default ``SONATA_REQUEST_TIMEOUT_S``;
- *does anyone still want the answer?* (``cancelled``) — flipped by the
  gRPC ``context.add_callback`` hook when the client disconnects.

Stages drop dead requests *before* spending device time on them: the
scheduler's gather loop filters expired/cancelled items out of a batch
before it is packed into a dispatch, and streaming loops check between
chunks.  Expired work fails with :class:`DeadlineExceeded`, which the
gRPC layer maps to ``DEADLINE_EXCEEDED``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..core import SonataError

#: Server-side default request timeout (seconds) when the client set no
#: gRPC deadline.  ``<= 0`` disables the server default (requests may
#: then only expire via an explicit client deadline).
TIMEOUT_ENV = "SONATA_REQUEST_TIMEOUT_S"
DEFAULT_TIMEOUT_S = 120.0


class DeadlineExceeded(SonataError):
    """The request ran out of time before (or while) being served."""


def default_timeout_s() -> Optional[float]:
    """The configured server-side default timeout, or None if disabled."""
    raw = os.environ.get(TIMEOUT_ENV)
    if raw is None:
        return DEFAULT_TIMEOUT_S
    try:
        value = float(raw)
    except ValueError:
        return DEFAULT_TIMEOUT_S
    return value if value > 0 else None


class Deadline:
    """An absolute point on the monotonic clock plus a cancellation flag.

    Immutable except for :meth:`cancel`; safe to share across the gRPC
    handler thread, the scheduler worker, and callback threads.
    """

    __slots__ = ("_expires_at", "_cancelled")

    def __init__(self, expires_at: Optional[float] = None):
        self._expires_at = expires_at  # monotonic seconds, None = never
        self._cancelled = threading.Event()

    # -- constructors --------------------------------------------------------
    @classmethod
    def after(cls, seconds: Optional[float]) -> "Deadline":
        if seconds is None:
            return cls(None)
        return cls(time.monotonic() + seconds)

    @classmethod
    def none(cls) -> "Deadline":
        """A deadline that never expires (still cancellable)."""
        return cls(None)

    @classmethod
    def from_grpc_context(cls, context,
                          default_s: Optional[float] = None) -> "Deadline":
        """Client deadline when set, else the server default.

        Also registers the context's termination callback (client
        disconnect / cancellation) when the context supports it, so a
        hung-up client stops costing device time.  Works with both real
        ``grpc.ServicerContext`` objects and the bare test doubles the
        suite uses (which may lack either attribute).
        """
        remaining = None
        time_remaining = getattr(context, "time_remaining", None)
        if time_remaining is not None:
            remaining = time_remaining()
        # "no client deadline" surfaces as None on some grpcio versions
        # and as int64-max-epoch seconds (~3e11) on others; both mean
        # "use the server default" (anything past a year is not a real
        # deadline, and huge values overflow C timestamp conversions in
        # downstream waits)
        if remaining is None or remaining > 365 * 24 * 3600:
            remaining = (default_s if default_s is not None
                         else default_timeout_s())
        dl = cls.after(remaining)
        add_callback = getattr(context, "add_callback", None)
        if add_callback is not None:
            # fires on client disconnect AND on normal completion; a
            # cancel after the response is finished is harmless
            try:
                add_callback(dl.cancel)
            except Exception:
                pass  # context already terminated
        return dl

    # -- queries -------------------------------------------------------------
    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self) -> None:
        self._cancelled.set()

    def remaining(self) -> Optional[float]:
        """Seconds left, None if unbounded.  May be negative once expired."""
        if self._expires_at is None:
            return None
        return self._expires_at - time.monotonic()

    def expired(self) -> bool:
        return (self._expires_at is not None
                and time.monotonic() >= self._expires_at)

    def alive(self) -> bool:
        """Still worth working on: neither expired nor cancelled."""
        return not self.expired() and not self.cancelled

    def raise_if_expired(self, what: str = "request") -> None:
        if self.expired():
            raise DeadlineExceeded(f"{what} deadline exceeded")

    def __repr__(self) -> str:
        rem = self.remaining()
        state = "cancelled" if self.cancelled else (
            "expired" if self.expired() else "alive")
        return (f"Deadline({state}, remaining="
                f"{'inf' if rem is None else f'{rem:.3f}s'})")
