"""Bounded admission control: fail fast instead of queueing unboundedly.

The reference gives every request its own blocking thread and lets the
thread pool's backlog grow without limit (``grpc/src/main.rs:381-409``);
our ``BatchScheduler`` queue was likewise unbounded.  Under overload that
turns into collapse: every request eventually times out, but only after
holding memory and queue slots for the full wait.

:class:`AdmissionController` enforces the standard two-tier bound:

- up to ``max_in_flight`` admitted requests actively execute;
- up to ``max_queue_depth`` more may wait (in practice inside the batch
  scheduler's queue or on the synthesis pool);
- everything beyond is **shed immediately** with a typed
  :class:`Overloaded` error the gRPC layer maps to
  ``RESOURCE_EXHAUSTED`` — the client can retry against another replica
  instead of waiting on a queue that will never drain in time.

The controller is a single counter against the sum of the two limits;
the split into "executing" vs "waiting" is carried by the scheduler
itself (whose own queue is also bounded, as defense in depth).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Callable, Iterator, Optional

from ..core import SonataError

MAX_IN_FLIGHT_ENV = "SONATA_MAX_IN_FLIGHT"
MAX_QUEUE_DEPTH_ENV = "SONATA_MAX_QUEUE_DEPTH"
DEFAULT_MAX_IN_FLIGHT = 32
DEFAULT_MAX_QUEUE_DEPTH = 128


class Overloaded(SonataError):
    """The server is at capacity; the request was shed, not queued."""


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class AdmissionController:
    """Thread-safe admitted-request counter with a hard ceiling."""

    def __init__(self, max_in_flight: Optional[int] = None,
                 max_queue_depth: Optional[int] = None):
        self.max_in_flight = (max_in_flight if max_in_flight is not None
                              else _env_int(MAX_IN_FLIGHT_ENV,
                                            DEFAULT_MAX_IN_FLIGHT))
        self.max_queue_depth = (max_queue_depth if max_queue_depth is not None
                                else _env_int(MAX_QUEUE_DEPTH_ENV,
                                              DEFAULT_MAX_QUEUE_DEPTH))
        if self.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if self.max_queue_depth < 0:
            raise ValueError("max_queue_depth must be >= 0")
        self._lock = threading.Lock()
        self._in_flight = 0
        self._shed = 0
        #: optional per-shed callback (the serving runtime points this at
        #: the degradation ladder); called outside the counter lock
        self.on_shed: Optional[Callable[[], None]] = None

    @property
    def capacity(self) -> int:
        return self.max_in_flight + self.max_queue_depth

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    @property
    def shed_total(self) -> int:
        with self._lock:
            return self._shed

    def try_acquire(self) -> bool:
        """Admit one request, or count a shed and return False."""
        with self._lock:
            if self._in_flight >= self.capacity:
                self._shed += 1
                shed = True
            else:
                self._in_flight += 1
                shed = False
        if shed and self.on_shed is not None:
            try:
                self.on_shed()
            except Exception:
                pass  # pressure accounting must never fail an RPC
        return not shed

    def release(self) -> None:
        with self._lock:
            if self._in_flight > 0:
                self._in_flight -= 1

    @contextlib.contextmanager
    def admit(self, what: str = "request") -> Iterator[None]:
        """Hold one admission slot for the duration of the block.

        Raises :class:`Overloaded` without blocking when the server is at
        ``max_in_flight + max_queue_depth`` admitted requests.
        """
        if not self.try_acquire():
            raise Overloaded(
                f"server at capacity ({self.capacity} admitted "
                f"{what}s: {self.max_in_flight} in flight + "
                f"{self.max_queue_depth} queued); shedding")
        try:
            yield
        finally:
            self.release()

    def snapshot(self) -> dict:
        with self._lock:
            return {"in_flight": self._in_flight, "shed": self._shed,
                    "max_in_flight": self.max_in_flight,
                    "max_queue_depth": self.max_queue_depth}
