"""Per-request wide-event ledger: one structured record per request.

Every tier of the stack already aggregates — scope keeps stage
quantiles, the fleet plane merges burn rates, tenancy meters tenants —
but aggregates cannot answer the operator's actual question: *which*
tenant's requests breached the SLO at 14:32, on which node, with what
cache and retry history?  The ledger answers it by assembling ONE wide
event per request across its whole path:

* **identity** — request_id, rpc, tenant, voice, node_id;
* **admission outcome** — cache hit / miss / follower, and every typed
  refusal (``node-quota``, ``router-quota``, ``tenant-shed``,
  ``fleet-shed``, ``voice-warming``, ``draining``, ``deadline``,
  ``overload``);
* **cost breakdown** — queue wait, decode iterations, dispatch count,
  padding rows, bytes out, TTFB, total duration (extracted from the
  request's trace spans at finalize, so the scheduler's existing
  attribution is the single source of truth);
* **disposition** — ``ok`` / ``error`` / ``refused`` / ``cancelled``.

Records are finalized exactly once at stream close and fed to a
byte-bounded in-memory ring plus an optional rotating NDJSON sink.
``GET /debug/requests`` serves the ring node-side; the mesh router
merges its hop record with the serving node's record by ``x-request-id``
(the stitched-trace pattern), so one document shows router reroutes
next to node-side cost.

Tail-based sampling: errors, refusals, and SLO-threshold violators are
ALWAYS kept; OK traffic is sampled at ``SONATA_LEDGER_SAMPLE``
(deterministic per request id, so router and node agree on keep/drop
without coordination).  The last-kept request id per incident kind is
exported as the ``sonata_ledger_exemplar`` gauge family, linking a
paging counter directly to the offending record.

Knobs (this module is the only reader):

* ``SONATA_LEDGER_MB`` — ring byte budget in MiB; unset/0/unparseable
  = ledger off, byte-for-byte pre-ledger request paths.
* ``SONATA_LEDGER_SAMPLE`` — OK-traffic keep probability in [0, 1]
  (default 1.0 = keep everything).
* ``SONATA_LEDGER_DIR`` — directory for the NDJSON sink
  (``ledger.ndjson``, rotated once to ``ledger.ndjson.1`` at the byte
  budget); unset = ring only.

Failure posture (the ``cache.lookup`` rule): :meth:`RequestLedger.emit`
wraps the whole finalize — including the ``ledger.emit`` failpoint — in
a degrade-to-no-record guard.  A broken ledger can never fail a
request; it only loses its own record and bumps
``sonata_ledger_emit_errors_total``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from . import faults
from .admission import Overloaded
from .deadlines import DeadlineExceeded
from .drain import Draining
from .scope import DEFAULT_SLO, parse_slos

log = logging.getLogger("sonata.ledger")

LEDGER_MB_ENV = "SONATA_LEDGER_MB"
LEDGER_SAMPLE_ENV = "SONATA_LEDGER_SAMPLE"
LEDGER_DIR_ENV = "SONATA_LEDGER_DIR"

#: record dispositions
OUTCOMES = ("ok", "error", "refused", "cancelled")

#: the typed-refusal vocabulary — every admission-refusal path in the
#: frontends lands in the ledger under exactly one of these
REFUSALS = ("node-quota", "router-quota", "tenant-shed", "fleet-shed",
            "voice-warming", "draining", "deadline", "overload")

#: exemplar incident kinds (gauge label values)
EXEMPLAR_KINDS = ("slo_breach", "refusal", "error")

SINK_NAME = "ledger.ndjson"


def resolve_ledger_mb() -> float:
    """Ring budget in MiB from ``SONATA_LEDGER_MB``; 0.0 = off.

    Unset, empty, unparseable, and negative all resolve to 0.0 — the
    ledger is opt-in and a typo'd knob must not take the server down.
    """
    raw = os.environ.get(LEDGER_MB_ENV, "").strip()
    if not raw:
        return 0.0
    try:
        mb = float(raw)
    except ValueError:
        log.warning("ignoring non-numeric %s=%r (ledger stays off)",
                    LEDGER_MB_ENV, raw)
        return 0.0
    return max(mb, 0.0)


def resolve_sample() -> float:
    """OK-traffic keep probability from ``SONATA_LEDGER_SAMPLE``.

    Default 1.0 (keep all); clamped to [0, 1].  Errors / refusals /
    SLO violators ignore this — tail sampling keeps 100% of them.
    """
    raw = os.environ.get(LEDGER_SAMPLE_ENV, "").strip()
    if not raw:
        return 1.0
    try:
        p = float(raw)
    except ValueError:
        log.warning("ignoring non-numeric %s=%r (sampling everything)",
                    LEDGER_SAMPLE_ENV, raw)
        return 1.0
    return min(max(p, 0.0), 1.0)


def resolve_sink_dir() -> Optional[str]:
    """NDJSON sink directory from ``SONATA_LEDGER_DIR`` (None = ring
    only)."""
    raw = os.environ.get(LEDGER_DIR_ENV, "").strip()
    return raw or None


def from_env() -> Optional["RequestLedger"]:
    """Build a ledger from the environment, or None when off.

    ``SONATA_LEDGER_MB`` unset/0 means *no ledger object at all*: no
    metric families, no per-request branches beyond one ``is None``
    check — the pre-ledger request path byte for byte.
    """
    mb = resolve_ledger_mb()
    if mb <= 0:
        return None
    try:
        slos = parse_slos()
    except ValueError:
        # the scope plane owns failing loudly on a typo'd SONATA_SLO;
        # the ledger only needs thresholds for tail sampling, so it
        # falls back to the defaults rather than double-crashing
        log.warning("malformed SONATA_SLO; ledger tail-sampling uses "
                    "the default SLO set", exc_info=True)
        slos = parse_slos(DEFAULT_SLO)
    return RequestLedger(max_bytes=int(mb * (1 << 20)),
                         sample=resolve_sample(),
                         sink_dir=resolve_sink_dir(),
                         slos=slos)


def refusal_kind(exc: BaseException) -> Optional[str]:
    """Map a typed serving exception to its refusal name (None = not a
    refusal — record it as an error instead).

    Quota/shed refusals are raised as plain :class:`Overloaded` from
    several distinct gates, so frontends pass an explicit ``refusal=``
    at those sites; this fallback covers the unambiguous types.
    """
    if isinstance(exc, Draining):
        return "draining"
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, Overloaded):
        return "overload"
    return None


def cost_fields_from_trace(trace) -> dict:
    """Extract the cost breakdown from a request trace's spans.

    The scheduler already attributes queue wait, dispatch membership,
    and padding rows into every participating trace (the Orca
    question); the ledger re-reads those spans rather than growing a
    second accounting channel.  Returns ``{}`` on any surprise — cost
    fields are best-effort garnish on a record that must always emit.
    """
    if trace is None:
        return {}
    try:
        queue_wait = 0.0
        dispatches = 0
        iterations = 0
        padding_rows = 0
        reroutes = 0
        cache = None
        for sp in trace.spans_snapshot():
            name = sp.name
            if name in ("queue-wait", "admission"):
                d = sp.duration_s
                if d:
                    queue_wait += d
            elif name == "dispatch":
                dispatches += 1
                try:
                    padding_rows += int(sp.attrs.get("padding_rows")
                                        or 0)
                except (TypeError, ValueError):
                    pass
            elif name == "decode-window":
                iterations += 1
            elif name == "cache-hit":
                cache = "hit"
            elif name == "cache-follow" or name == "fleetcache-follow":
                cache = "follow"
            elif name == "mesh-reroute":
                reroutes += 1
        out: dict = {"queue_wait_s": round(queue_wait, 6),
                     "dispatches": dispatches,
                     "padding_rows": padding_rows}
        if iterations:
            out["iterations"] = iterations
        if cache is not None:
            out["cache"] = cache
        if reroutes:
            out["reroutes"] = reroutes
        return out
    except Exception:
        log.debug("cost extraction degraded to no-fields",
                  exc_info=True)
        return {}


class LedgerRecord:
    """One request's in-flight wide event (cheap until finalize).

    ``begin()`` stamps identity and a monotonic start; the frontends
    :meth:`note` fields as they learn them; :meth:`RequestLedger.emit`
    finalizes exactly once (the ``emitted`` latch makes double-finalize
    from nested error paths a no-op).
    """

    __slots__ = ("fields", "t0", "emitted")

    def __init__(self, rpc: str, request_id: str, **fields) -> None:
        self.t0 = time.monotonic()
        self.emitted = False
        self.fields: dict = {"request_id": request_id, "rpc": rpc}
        self.note(**fields)

    def note(self, **fields) -> None:
        """Attach fields (None values are skipped, not recorded)."""
        for key, value in fields.items():
            if value is not None:
                self.fields[key] = value


class RequestLedger:
    """Byte-bounded ring + optional NDJSON sink of wide events."""

    def __init__(self, max_bytes: int, sample: float = 1.0,
                 sink_dir: Optional[str] = None,
                 slos=()) -> None:
        self.max_bytes = int(max_bytes)
        self.sample = float(sample)
        self.node_id: Optional[str] = None
        self._slos = tuple(slos)
        self._lock = threading.Lock()
        # (nbytes, record) pairs, oldest first; evicted oldest-OK-first
        # so a burst of healthy traffic can never push an incident
        # record out of the ring
        self._ring: List[tuple] = []
        self._ring_bytes = 0
        self._outcomes: Dict[str, int] = {o: 0 for o in OUTCOMES}
        self._stats: Dict[str, int] = {
            "sampled_out": 0, "emit_errors": 0, "evictions": 0,
            "sink_rotations": 0}
        # last-kept request id per incident kind, exported as the
        # exemplar gauge (value = finalize wall time)
        self._exemplars: Dict[str, tuple] = {}
        self._exemplar_metric = None
        self._exported_rids: Dict[str, str] = {}
        self._node_fetcher: Optional[Callable] = None
        self._closed = False
        # sink state (its own lock: file IO must not serialize behind
        # ring queries)
        self._sink_lock = threading.Lock()
        self._sink_path: Optional[str] = None
        self._sink_bytes = 0
        if sink_dir:
            try:
                os.makedirs(sink_dir, exist_ok=True)
                self._sink_path = os.path.join(sink_dir, SINK_NAME)
                if os.path.exists(self._sink_path):
                    self._sink_bytes = os.path.getsize(self._sink_path)
            except OSError:
                log.warning("ledger sink dir %r unusable (ring only)",
                            sink_dir, exc_info=True)
                self._sink_path = None

    # -- record lifecycle ---------------------------------------------------

    def begin(self, rpc: str, request_id: str, *,
              voice: Optional[str] = None,
              tenant: Optional[str] = None) -> LedgerRecord:
        """Open a record.  Lock-free and allocation-light: the hot path
        pays one dict until finalize."""
        return LedgerRecord(rpc, request_id, voice=voice, tenant=tenant,
                            node_id=self.node_id)

    def emit(self, record: Optional[LedgerRecord], *,
             outcome: str = "ok", error: Optional[str] = None,
             refusal: Optional[str] = None) -> None:
        """Finalize ``record`` — never raises.

        Any exception (including the ``ledger.emit`` failpoint)
        degrades to no-record: the request already succeeded or failed
        on its own terms, and observability must not change that.
        """
        if record is None or record.emitted or self._closed:
            return
        record.emitted = True
        try:
            faults.fire("ledger.emit")
            if refusal is not None:
                outcome = "refused"
            rec = dict(record.fields)
            rec["outcome"] = outcome
            if error is not None:
                rec["error"] = error
            if refusal is not None:
                rec["refusal"] = refusal
            rec["dur_s"] = round(time.monotonic() - record.t0, 6)
            rec["ts"] = round(time.time(), 3)
            self._ingest(rec)
        except Exception:
            with self._lock:
                self._stats["emit_errors"] += 1
            log.debug("ledger emit degraded to no-record",
                      exc_info=True)

    def _ingest(self, rec: dict) -> None:
        outcome = rec.get("outcome", "ok")
        rid = rec.get("request_id", "")
        violated = self._slo_violations(rec) if outcome == "ok" else []
        if violated:
            rec["slo"] = violated
        # tail sampling: every incident is kept; only clean-and-fast
        # OK traffic rolls the (deterministic) dice
        keep = (outcome != "ok" or bool(violated)
                or self.sample_decision(rid))
        exemplar = None
        if violated:
            exemplar = "slo_breach"
        elif outcome == "refused":
            exemplar = "refusal"
        elif outcome == "error":
            exemplar = "error"
        line = json.dumps(rec, separators=(",", ":"), sort_keys=True,
                          default=str)
        nbytes = len(line) + 1
        with self._lock:
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
            if not keep:
                self._stats["sampled_out"] += 1
            else:
                self._ring.append((nbytes, rec))
                self._ring_bytes += nbytes
                while self._ring_bytes > self.max_bytes and self._ring:
                    idx = next(
                        (i for i, (_n, r) in enumerate(self._ring)
                         if r.get("outcome") == "ok"), 0)
                    freed, _dropped = self._ring.pop(idx)
                    self._ring_bytes -= freed
                    self._stats["evictions"] += 1
            if exemplar is not None and keep:
                self._exemplars[exemplar] = (rid, rec["ts"])
        if keep:
            self._export_exemplars()
            self._sink_write(line)

    def _slo_violations(self, rec: dict) -> List[str]:
        """Names of latency SLOs this record breaches (error-rate SLOs
        are population properties — not a per-record question)."""
        violated: List[str] = []
        try:
            for spec in self._slos:
                if getattr(spec, "kind", None) != "latency":
                    continue
                stage = getattr(spec, "stage", None)
                if stage == "ttfb":
                    value = rec.get("ttfb_s")
                elif stage == "e2e":
                    value = rec.get("dur_s")
                else:
                    continue
                threshold = getattr(spec, "threshold_s", None)
                if (value is not None and threshold is not None
                        and value > threshold):
                    violated.append(getattr(spec, "name", stage))
        except Exception:
            log.debug("slo check degraded to no-violations",
                      exc_info=True)
            return []
        return violated

    def sample_decision(self, request_id: str) -> bool:
        """Deterministic keep/drop for OK traffic.

        Hash-derived from the request id so every hop (router, node,
        test) agrees on the same decision without coordination, and so
        tests pin exact capture sets with chosen ids.
        """
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        digest = hashlib.blake2b(request_id.encode("utf-8", "replace"),
                                 digest_size=8).digest()
        unit = int.from_bytes(digest, "big") / float(1 << 64)
        return unit < self.sample

    # -- exemplars ----------------------------------------------------------

    def _export_exemplars(self) -> None:
        """Mirror the last-kept incident ids onto the exemplar gauge.

        One series per kind: the previous request_id's series is
        removed before the new one is set, so the family stays bounded
        at ``len(EXEMPLAR_KINDS)`` series no matter the traffic.
        """
        metric = self._exemplar_metric
        if metric is None:
            return
        with self._lock:
            snapshot = dict(self._exemplars)
        for kind, (rid, ts) in snapshot.items():
            try:
                old = self._exported_rids.get(kind)
                if old is not None and old != rid:
                    metric.remove(kind=kind, request_id=old)
                metric.labels(kind=kind, request_id=rid).set(ts)
                self._exported_rids[kind] = rid
            except Exception:
                log.debug("exemplar export degraded", exc_info=True)

    # -- sink ---------------------------------------------------------------

    def _sink_write(self, line: str) -> None:
        if self._sink_path is None:
            return
        data = (line + "\n").encode("utf-8")
        with self._sink_lock:
            rotate = bool(
                self._sink_bytes
                and self._sink_bytes + len(data) > self.max_bytes)
            self._sink_bytes = (len(data) if rotate
                                else self._sink_bytes + len(data))
        # the I/O runs OUTSIDE the lock: the bookkeeping above elects
        # exactly one rotator per threshold crossing, and O_APPEND
        # whole-line writes keep concurrent appenders' lines intact —
        # a line landing in the just-rotated file during the rename
        # window is acceptable for a best-effort debug sink
        try:
            if rotate:
                os.replace(self._sink_path, self._sink_path + ".1")
                with self._lock:
                    self._stats["sink_rotations"] += 1
            fd = os.open(self._sink_path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
        except OSError:
            log.debug("ledger sink write degraded to ring-only",
                      exc_info=True)

    # -- queries ------------------------------------------------------------

    def query(self, tenant: Optional[str] = None,
              voice: Optional[str] = None,
              outcome: Optional[str] = None,
              since: Optional[float] = None,
              request_id: Optional[str] = None,
              limit: int = 100) -> List[dict]:
        """Filtered view of the ring, newest first.

        When querying by ``request_id`` on a router whose record names
        a serving node, the node's own record is fetched and merged in
        under ``node_record`` (the stitched-trace pattern) — one
        document, both hops.
        """
        limit = max(int(limit), 0)
        out: List[dict] = []
        with self._lock:
            for _nbytes, rec in reversed(self._ring):
                if tenant is not None and rec.get("tenant") != tenant:
                    continue
                if voice is not None and rec.get("voice") != voice:
                    continue
                if outcome is not None and rec.get("outcome") != outcome:
                    continue
                if since is not None and rec.get("ts", 0) < since:
                    continue
                if (request_id is not None
                        and rec.get("request_id") != request_id):
                    continue
                out.append(dict(rec))
                if len(out) >= limit:
                    break
        fetcher = self._node_fetcher
        if request_id is not None and fetcher is not None:
            for rec in out:
                node = (rec.get("router") or {}).get("node")
                if not node or "node_record" in rec:
                    continue
                try:
                    fetched = fetcher(request_id, node)
                except Exception:
                    log.debug("node-record fetch degraded",
                              exc_info=True)
                    fetched = None
                if fetched:
                    rec["node_record"] = fetched
        return out

    def set_node_record_fetcher(self, fn: Optional[Callable]) -> None:
        """Router-side hook: ``fn(request_id, node_id) -> dict|None``
        fetches the serving node's own record for query-time merge."""
        self._node_fetcher = fn

    # -- stats / metrics ----------------------------------------------------

    def stat(self, name: str) -> float:
        with self._lock:
            if name == "ring_bytes":
                return float(self._ring_bytes)
            if name == "ring_records":
                return float(len(self._ring))
            return float(self._stats.get(name, 0))

    def outcome_total(self, outcome: str) -> float:
        with self._lock:
            return float(self._outcomes.get(outcome, 0))

    def bind_metrics(self, registry) -> None:
        """Register the ledger's families (only when the ledger exists,
        so ``SONATA_LEDGER_MB=0`` pins zero new series)."""
        records = registry.counter(
            "sonata_ledger_records_total",
            "Finalized wide events by disposition.")
        for outcome in OUTCOMES:
            records.labels(outcome=outcome).set_function(
                lambda o=outcome: self.outcome_total(o))
        for family, help_text in (
                ("sonata_ledger_sampled_out_total",
                 "OK records dropped by probabilistic sampling."),
                ("sonata_ledger_emit_errors_total",
                 "Record finalizations degraded to no-record."),
                ("sonata_ledger_evictions_total",
                 "Ring records evicted to hold the byte budget."),
                ("sonata_ledger_sink_rotations_total",
                 "NDJSON sink rotations at the byte budget.")):
            stat_name = family[len("sonata_ledger_"):-len("_total")]
            registry.counter(family, help_text).set_function(
                lambda s=stat_name: self.stat(s))
        registry.gauge(
            "sonata_ledger_ring_bytes",
            "Bytes held by the in-memory record ring.").set_function(
            lambda: self.stat("ring_bytes"))
        registry.gauge(
            "sonata_ledger_ring_records",
            "Records held by the in-memory ring.").set_function(
            lambda: self.stat("ring_records"))
        self._exemplar_metric = registry.gauge(
            "sonata_ledger_exemplar",
            "Last-kept request id per incident kind (value = finalize "
            "unix time); links SLO-breach and refusal counters to the "
            "offending ledger record.")

    def ledger_view(self) -> dict:
        """Point-in-time stats document (debug / tests)."""
        with self._lock:
            return {"ring_records": len(self._ring),
                    "ring_bytes": self._ring_bytes,
                    "max_bytes": self.max_bytes,
                    "sample": self.sample,
                    "outcomes": dict(self._outcomes),
                    **dict(self._stats)}

    def close(self) -> None:
        """Stop accepting emits (ring stays queryable for teardown
        introspection; nothing to flush — the sink writes through)."""
        self._closed = True
