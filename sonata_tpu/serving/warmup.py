"""Bucket-lattice AOT warmup: zero cold compiles after a restart.

PR-4's tracing measured the cliff this module removes: the same request
costs 4556 ms with ``compile=cold`` and 30 ms ``cached``.  The old
readiness warmup synthesized exactly **one utterance per replica**, so
after every rolling restart the first real request on every *other*
(batch, text, frame) bucket paid that cliff — a multi-second p999 stall
per bucket, at the worst possible moment (right after a deploy, on
every replica at once).

This module drives the replacement:

- the model enumerates its bucket lattice (``lattice_shapes(mode)``,
  derived from :mod:`sonata_tpu.utils.buckets`) and compiles each shape
  ahead of traffic (``warm_shape`` — a synthetic dummy-argument
  dispatch through the same jit cache real traffic uses, which also
  lands every executable in the persistent compile cache so the
  *second* boot warms from disk in a fraction of cold time);
- ``SONATA_WARMUP_LATTICE=full|minimal|off`` picks coverage: ``full``
  adds the canonical coalesced batch size and the frame-bucket
  neighbors (estimator drift headroom), ``minimal`` is batch-1 with the
  estimated frame bucket per text bucket, ``off`` keeps the legacy
  one-utterance warmup only;
- the whole pass is bounded by ``SONATA_WARMUP_BUDGET_S``.  **Budget
  expiry keeps readiness false** (typed :class:`WarmupBudgetExceeded`,
  one loud log line): a replica that cannot warm inside its budget must
  not join the serving set half-cold — the orchestrator retries or
  rolls back instead of sending users into compiles;
- progress is exported as the ``sonata_warmup_progress`` gauge
  (:class:`WarmupProgress`), so a stuck warmup is a flat line on a
  dashboard, not a silent boot hang.

Models without the lattice contract (no ``lattice_shapes``) fall back
to the one-utterance warmup — the protocol is additive.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..core import OperationError

log = logging.getLogger("sonata.serving")

WARMUP_LATTICE_ENV = "SONATA_WARMUP_LATTICE"
WARMUP_BUDGET_ENV = "SONATA_WARMUP_BUDGET_S"
WARMUP_WORKERS_ENV = "SONATA_WARMUP_WORKERS"
MODES = ("full", "minimal", "off")
DEFAULT_MODE = "full"
DEFAULT_WARMUP_BUDGET_S = 600.0
#: concurrent compile workers per model — the same constant the prewarm
#: path uses ("4 workers roughly quarter a cold boot's multi-minute
#: warm"): distinct shapes' XLA compiles are independent and release
#: the GIL.  Warm (cache-hit) boots are tracing-bound and gain little;
#: the CI smoke pins 1 so its cold/warm A/B isolates the cache effect.
DEFAULT_WARM_WORKERS = 4


class WarmupBudgetExceeded(OperationError):
    """The bucket-lattice warmup ran past ``SONATA_WARMUP_BUDGET_S``.

    Readiness stays false: joining the serving set half-warm would hand
    real users the exact compile stalls the lattice exists to prevent."""


def resolve_mode(mode: Optional[str] = None) -> str:
    """Explicit arg > ``SONATA_WARMUP_LATTICE`` > ``full``.  A typo'd
    mode fails loudly at boot (same contract as the SLO table): a fleet
    silently falling back to one-utterance warmup is a p999 regression
    nobody would see until the next deploy."""
    raw = (mode if mode is not None
           else os.environ.get(WARMUP_LATTICE_ENV, "")).strip().lower()
    if not raw:
        return DEFAULT_MODE
    if raw not in MODES:
        raise OperationError(
            f"{WARMUP_LATTICE_ENV}={raw!r} is not one of "
            f"{'/'.join(MODES)}")
    return raw


def resolve_budget_s(budget_s: Optional[float] = None) -> float:
    """Explicit arg > ``SONATA_WARMUP_BUDGET_S`` > 600 s."""
    if budget_s is not None:
        return max(0.0, float(budget_s))
    try:
        return max(0.0, float(os.environ.get(WARMUP_BUDGET_ENV,
                                             DEFAULT_WARMUP_BUDGET_S)))
    except ValueError:
        return DEFAULT_WARMUP_BUDGET_S


class WarmupProgress:
    """Thread-safe warmup progress, driving the ``sonata_warmup_progress``
    gauge: 0.0 at boot, ``done/total`` while warming, 1.0 once every
    enumerated shape compiled.  A gauge that stops moving below 1.0 IS
    the stuck-warmup signal."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.done = 0
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.failed_reason: Optional[str] = None

    def reset(self) -> None:
        with self._lock:
            self.total = 0
            self.done = 0
            self.started_at = time.monotonic()
            self.finished_at = None
            self.failed_reason = None

    def add_total(self, n: int) -> None:
        with self._lock:
            self.total += n

    def note_done(self, n: int = 1) -> None:
        with self._lock:
            self.done += n

    def finish(self, failed_reason: Optional[str] = None) -> None:
        with self._lock:
            self.finished_at = time.monotonic()
            self.failed_reason = failed_reason

    def fraction(self) -> float:
        with self._lock:
            if self.total <= 0:
                # no lattice enumerated (mode off / legacy models): a
                # *finished* warmup still reads 1.0 so dashboards can
                # alert on "boot finished but progress < 1"
                return 1.0 if self.finished_at is not None else 0.0
            return min(1.0, self.done / self.total)

    def snapshot(self) -> dict:
        with self._lock:
            return {"total": self.total, "done": self.done,
                    "failed_reason": self.failed_reason,
                    "finished": self.finished_at is not None}


def resolve_workers(workers: Optional[int] = None) -> int:
    """Explicit arg > ``SONATA_WARMUP_WORKERS`` > 4, floored at 1."""
    if workers is not None:
        return max(1, int(workers))
    try:
        return max(1, int(os.environ.get(WARMUP_WORKERS_ENV,
                                         DEFAULT_WARM_WORKERS)))
    except ValueError:
        return DEFAULT_WARM_WORKERS


def warm_model_lattice(model, *, mode: str, deadline: float,
                       progress: Optional[WarmupProgress] = None,
                       label: str = "",
                       workers: Optional[int] = None) -> int:
    """Compile one model's bucket lattice ahead of traffic.

    ``model`` supplies ``lattice_shapes(mode) -> [(b, t, f), ...]`` and
    ``warm_shape((b, t, f))``; models without the contract return 0
    shapes (the caller keeps its one-utterance warmup).  Shapes compile
    ``workers``-wide (independent XLA compiles, the prewarm pattern).
    ``deadline`` is a ``time.monotonic()`` instant shared across every
    model in the boot (one budget covers the whole process, not one per
    replica); each queued shape re-checks it before compiling, so a
    blown budget stops the lattice at the next shape boundary and
    raises :class:`WarmupBudgetExceeded` — readiness stays false.
    Returns the number of shapes warmed for this model.
    """
    from concurrent.futures import ThreadPoolExecutor

    shapes_fn = getattr(model, "lattice_shapes", None)
    if shapes_fn is None:
        return 0
    shapes = list(shapes_fn(mode))
    if progress is not None:
        progress.add_total(len(shapes))
    if not shapes:
        return 0
    workers = resolve_workers(workers)

    def warm_one(shape) -> None:
        # checked per shape ON the worker: all shapes are queued up
        # front, so a submit-time check would pass for every one of
        # them at t=0 and bound nothing
        if time.monotonic() >= deadline:
            raise WarmupBudgetExceeded(
                f"warmup lattice {label or 'model'} ran past the "
                f"{WARMUP_BUDGET_ENV} budget; readiness stays false")
        model.warm_shape(shape)
        if progress is not None:
            progress.note_done()

    warmed = 0
    expired: Optional[WarmupBudgetExceeded] = None
    with ThreadPoolExecutor(max(1, min(workers, len(shapes))),
                            thread_name_prefix="sonata_lattice") as ex:
        for fut in [ex.submit(warm_one, s) for s in shapes]:
            try:
                fut.result()
                warmed += 1
            except WarmupBudgetExceeded as e:
                expired = e  # keep draining: remaining futures fail fast
    if expired is not None:
        raise WarmupBudgetExceeded(
            f"warmup lattice {label or 'model'} ran past the "
            f"{WARMUP_BUDGET_ENV} budget with {warmed}/{len(shapes)} "
            f"shapes warm; readiness stays false") from expired
    log.info("warmup lattice %s: %d shape(s) warm (mode=%s, "
             "%d workers)", label or "model", warmed, mode,
             min(workers, len(shapes)))
    return warmed
