"""Liveness and readiness for rolling restarts.

Two different questions, two different endpoints:

- **Liveness** (``/healthz``): is the process able to make progress at
  all?  True from startup; an orchestrator restarts the pod when it goes
  false (we only flip it on unrecoverable internal failure).
- **Readiness** (``/readyz``, and the ``CheckHealth`` gRPC unary): should
  a load balancer send traffic *now*?  False until the preloaded voices
  have finished loading AND each has synthesized one warmup utterance —
  the warmup forces the XLA compile of the common executables, so the
  first real request never eats a multi-second (cold cache: multi-minute)
  compile.  During a rolling restart the new replica therefore joins the
  serving set only once it can answer at steady-state latency.

Both are also exported as gauges (``sonata_up``, ``sonata_ready``) so the
scrape plane sees the same truth the probes do.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .metrics import MetricsRegistry


class HealthState:
    """Thread-safe liveness/readiness flags with a human-readable reason."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._live = True
        self._ready = threading.Event()
        self._reason = "starting: voices not loaded"
        self._ready_at: Optional[float] = None
        if registry is not None:
            registry.gauge(
                "sonata_up", "Process liveness (1 = live)."
            ).set_function(lambda: 1.0 if self.live else 0.0)
            registry.gauge(
                "sonata_ready",
                "Readiness gate (1 = voices loaded and warmed)."
            ).set_function(lambda: 1.0 if self.ready else 0.0)

    # -- liveness ------------------------------------------------------------
    @property
    def live(self) -> bool:
        with self._lock:
            return self._live

    def set_unhealthy(self, reason: str) -> None:
        """Unrecoverable internal failure: ask the orchestrator for a
        restart (also drops readiness)."""
        with self._lock:
            self._live = False
            self._reason = reason
        self._ready.clear()

    # -- readiness -----------------------------------------------------------
    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    def set_ready(self, reason: str = "ready") -> None:
        with self._lock:
            self._reason = reason
            if self._ready_at is None:
                self._ready_at = time.monotonic()
        self._ready.set()

    def set_not_ready(self, reason: str) -> None:
        """Drop out of the serving set (e.g. draining before shutdown)."""
        with self._lock:
            self._reason = reason
        self._ready.clear()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return self._ready.wait(timeout)

    def snapshot(self) -> dict:
        with self._lock:
            return {"live": self._live, "ready": self._ready.is_set(),
                    "reason": self._reason}
