"""Liveness and readiness for rolling restarts.

Two different questions, two different endpoints:

- **Liveness** (``/healthz``): is the process able to make progress at
  all?  True from startup; an orchestrator restarts the pod when it goes
  false (we only flip it on unrecoverable internal failure).
- **Readiness** (``/readyz``, and the ``CheckHealth`` gRPC unary): should
  a load balancer send traffic *now*?  False until the preloaded voices
  have finished loading AND each has synthesized one warmup utterance —
  the warmup forces the XLA compile of the common executables, so the
  first real request never eats a multi-second (cold cache: multi-minute)
  compile.  During a rolling restart the new replica therefore joins the
  serving set only once it can answer at steady-state latency.

Both are also exported as gauges (``sonata_up``, ``sonata_ready``) so the
scrape plane sees the same truth the probes do.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .metrics import MetricsRegistry


class HealthState:
    """Thread-safe liveness/readiness flags with a human-readable reason."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._live = True
        self._ready = threading.Event()
        self._reason = "starting: voices not loaded"
        self._ready_at: Optional[float] = None
        #: stable node identity (SONATA_NODE_ID or host:port), set by
        #: ServingRuntime.set_node_id once the frontend knows its bind
        #: address; surfaced on /readyz and CheckHealth so fleet-side
        #: logs name this process instead of an opaque channel
        self.node_id: Optional[str] = None
        #: loaded-voice ids (maintained by ServingRuntime.register_voice
        #: / unregister_voice), surfaced as the ``voices=`` line on
        #: /readyz — the ACTUAL-state signal the sonata-mesh placement
        #: reconciler diffs against its desired state.  Present even
        #: when empty: an explicit empty set ("this node holds no
        #: voices") is exactly the news a restarted node must deliver.
        self._voice_ids: set = set()
        #: named predicates evaluated at every readiness read: the
        #: process is ready only when the event is set AND every gate
        #: holds.  This is how live conditions (e.g. "this voice's
        #: replica pool has a healthy replica") flip /readyz without
        #: anyone having to call set_not_ready at the right moment —
        #: and flip it back on recovery just as automatically.
        self._gates: dict = {}
        if registry is not None:
            registry.gauge(
                "sonata_up", "Process liveness (1 = live)."
            ).set_function(lambda: 1.0 if self.live else 0.0)
            registry.gauge(
                "sonata_ready",
                "Readiness gate (1 = voices loaded and warmed)."
            ).set_function(lambda: 1.0 if self.ready else 0.0)

    # -- liveness ------------------------------------------------------------
    @property
    def live(self) -> bool:
        with self._lock:
            return self._live

    def set_unhealthy(self, reason: str) -> None:
        """Unrecoverable internal failure: ask the orchestrator for a
        restart (also drops readiness)."""
        with self._lock:
            self._live = False
            self._reason = reason
        self._ready.clear()

    # -- readiness -----------------------------------------------------------
    @property
    def ready(self) -> bool:
        return self._ready.is_set() and self._failing_gate() is None

    def _failing_gate(self) -> Optional[str]:
        """Name of the first failing readiness gate, or None.  A gate
        that raises counts as failing (fail-safe: an error evaluating
        health must read as unhealthy, never as healthy)."""
        with self._lock:
            gates = list(self._gates.items())
        for name, fn in gates:
            try:
                if not fn():
                    return name
            except Exception:
                return name
        return None

    def add_readiness_gate(self, name: str, fn) -> None:
        """Register a zero-arg predicate that must hold for readiness."""
        with self._lock:
            self._gates[name] = fn

    def remove_readiness_gate(self, name: str) -> None:
        with self._lock:
            self._gates.pop(name, None)

    @property
    def reason(self) -> str:
        gate = self._failing_gate()
        if gate is not None and self._ready.is_set():
            return f"readiness gate failing: {gate}"
        with self._lock:
            return self._reason

    def set_ready(self, reason: str = "ready") -> None:
        with self._lock:
            self._reason = reason
            if self._ready_at is None:
                self._ready_at = time.monotonic()
        self._ready.set()

    def set_not_ready(self, reason: str) -> None:
        """Drop out of the serving set (e.g. draining before shutdown)."""
        with self._lock:
            self._reason = reason
        self._ready.clear()

    # -- loaded voices (the placement reconciler's actual state) -------------
    def note_voice(self, voice_id: str) -> None:
        with self._lock:
            self._voice_ids.add(voice_id)

    def drop_voice(self, voice_id: str) -> None:
        with self._lock:
            self._voice_ids.discard(voice_id)

    def voices_view(self) -> list:
        """Sorted loaded-voice ids (what /readyz renders as
        ``voices=``)."""
        with self._lock:
            return sorted(self._voice_ids)

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return self._ready.wait(timeout)

    def snapshot(self) -> dict:
        ready = self.ready
        reason = self.reason
        with self._lock:
            return {"live": self._live, "ready": ready, "reason": reason,
                    "node_id": self.node_id}
