"""Graceful-degradation ladder: named pressure levels with hysteresis.

Admission shedding and the hung-dispatch watchdog tell the process it is
in trouble; until now nothing *acted* on that signal — the server kept
its full coalescing windows, kept accepting batch work, and kept
advertising readiness while drowning.  The ladder turns sustained
pressure into staged, reversible load-shedding policy:

====  ================  =====================================================
lvl   name              effect
====  ================  =====================================================
0     normal            —
1     shrink-coalesce   batch-gather windows collapse to zero
                        (:func:`gather_scale`): dispatches go out
                        per-request, trading throughput for latency and
                        queue drain
2     reject-batch      batch/long-form synthesis (PARALLEL/BATCHED modes)
                        sheds with ``Overloaded`` before interactive work
                        is touched
3     readiness-off     the ``degradation`` readiness gate fails —
                        ``/readyz`` goes 503 and the balancer routes
                        around the whole process
====  ================  =====================================================

Stepping **up**: each recorded pressure event (a shed, a watchdog fire)
lands in a sliding window; when the window holds
``SONATA_DEGRADE_SHED_THRESHOLD`` sheds or
``SONATA_DEGRADE_WATCHDOG_THRESHOLD`` watchdog fires, the level rises by
one and the window restarts (another full window of pressure is needed
for the next step — no instant 0→3 jumps from one burst).

Stepping **down** (hysteresis): a level is held until the process has
been quiet — no pressure events — for ``SONATA_DEGRADE_RECOVER_S``, then
recovery descends one level per quiet period.  Evaluation is lazy, on
reads (every request and every metrics scrape call
:meth:`DegradationLadder.current_level`), so no timer thread exists.

Every transition is one log line and a move of the
``sonata_degradation_level`` gauge (exported by ``ServingRuntime``).
The process-global install (:func:`install`) lets deep layers — the
batch scheduler's gather loop, its watchdog — consult and feed the
ladder without threading the runtime through the model protocol.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

log = logging.getLogger("sonata.serving")

WINDOW_ENV = "SONATA_DEGRADE_WINDOW_S"
SHED_THRESHOLD_ENV = "SONATA_DEGRADE_SHED_THRESHOLD"
WATCHDOG_THRESHOLD_ENV = "SONATA_DEGRADE_WATCHDOG_THRESHOLD"
BURN_THRESHOLD_ENV = "SONATA_DEGRADE_BURN_THRESHOLD"
RECOVER_ENV = "SONATA_DEGRADE_RECOVER_S"

DEFAULT_WINDOW_S = 30.0
DEFAULT_SHED_THRESHOLD = 20
DEFAULT_WATCHDOG_THRESHOLD = 2
#: SLO-burn pressure events (the scope's 1 Hz tick emits one per second
#: of sustained over-threshold fast-window burn, when
#: SONATA_DEGRADE_ON_BURN enables the coupling) per window per step
DEFAULT_BURN_THRESHOLD = 10
DEFAULT_RECOVER_S = 15.0

#: level names, index == level (also the gauge's documented scale)
LEVEL_NAMES = ("normal", "shrink-coalesce", "reject-batch",
               "readiness-off")
MAX_LEVEL = len(LEVEL_NAMES) - 1


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class DegradationLadder:
    """Pressure-event windows + the current level, with hysteresis."""

    def __init__(self, *, window_s: Optional[float] = None,
                 shed_threshold: Optional[int] = None,
                 watchdog_threshold: Optional[int] = None,
                 burn_threshold: Optional[int] = None,
                 recover_s: Optional[float] = None,
                 on_change: Optional[Callable[[int, str], None]] = None):
        self.window_s = max(0.1, window_s if window_s is not None
                            else _env_float(WINDOW_ENV, DEFAULT_WINDOW_S))
        #: 0 disables the corresponding trigger
        self.shed_threshold = max(0, (
            shed_threshold if shed_threshold is not None
            else _env_int(SHED_THRESHOLD_ENV, DEFAULT_SHED_THRESHOLD)))
        self.watchdog_threshold = max(0, (
            watchdog_threshold if watchdog_threshold is not None
            else _env_int(WATCHDOG_THRESHOLD_ENV,
                          DEFAULT_WATCHDOG_THRESHOLD)))
        self.burn_threshold = max(0, (
            burn_threshold if burn_threshold is not None
            else _env_int(BURN_THRESHOLD_ENV, DEFAULT_BURN_THRESHOLD)))
        self.recover_s = max(0.05, (
            recover_s if recover_s is not None
            else _env_float(RECOVER_ENV, DEFAULT_RECOVER_S)))
        self.on_change = on_change
        self._lock = threading.Lock()
        self._sheds: "deque[float]" = deque()
        self._watchdogs: "deque[float]" = deque()
        self._burns: "deque[float]" = deque()
        self._level = 0
        self._peak_level = 0
        self._transitions = 0
        self._last_change = time.monotonic()
        self._last_event = 0.0

    # -- event intake ---------------------------------------------------------
    def record_shed(self) -> None:
        """One request shed for capacity (admission, scheduler queue, or
        a pool with no healthy replica)."""
        self._event(self._sheds)

    def record_watchdog(self) -> None:
        """One dispatch killed by the hung-dispatch watchdog."""
        self._event(self._watchdogs)

    def record_burn(self) -> None:
        """One second of sustained SLO fast-window burn over the page
        threshold (fed by the scope's recorder tick when
        ``SONATA_DEGRADE_ON_BURN`` couples the two) — the ladder reacts
        to user-visible latency, not just sheds."""
        self._event(self._burns)

    def _event(self, dq: "deque[float]") -> None:
        now = time.monotonic()
        stepped_to = None
        with self._lock:
            dq.append(now)
            self._last_event = now
            self._prune_locked(now)
            if self._pressure_locked() and self._level < MAX_LEVEL:
                self._level += 1
                self._peak_level = max(self._peak_level, self._level)
                self._transitions += 1
                self._last_change = now
                # a full fresh window of pressure is needed per step
                self._sheds.clear()
                self._watchdogs.clear()
                self._burns.clear()
                stepped_to = self._level
        if stepped_to is not None:
            self._announce(stepped_to, "pressure")

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        for dq in (self._sheds, self._watchdogs, self._burns):
            while dq and dq[0] < horizon:
                dq.popleft()

    def _pressure_locked(self) -> bool:
        return ((self.shed_threshold > 0
                 and len(self._sheds) >= self.shed_threshold)
                or (self.watchdog_threshold > 0
                    and len(self._watchdogs) >= self.watchdog_threshold)
                or (self.burn_threshold > 0
                    and len(self._burns) >= self.burn_threshold))

    # -- level ----------------------------------------------------------------
    def current_level(self) -> int:
        """The level after lazy hysteresis decay (one step down per quiet
        ``recover_s``); called on every request and metrics scrape."""
        now = time.monotonic()
        stepped_to = None
        with self._lock:
            if (self._level > 0
                    and now - self._last_event >= self.recover_s
                    and now - self._last_change >= self.recover_s):
                self._level -= 1
                self._transitions += 1
                self._last_change = now
                stepped_to = self._level
            level = self._level
        if stepped_to is not None:
            self._announce(stepped_to, "recovery")
        return level

    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self.current_level()]

    def reject_heavy(self) -> bool:
        """Level >= 2: batch/long-form work sheds before interactive."""
        return self.current_level() >= 2

    def _announce(self, level: int, why: str) -> None:
        msg = ("degradation level %d (%s) via %s: window=%gs "
               "shed_threshold=%d watchdog_threshold=%d recover=%gs")
        args = (level, LEVEL_NAMES[level], why, self.window_s,
                self.shed_threshold, self.watchdog_threshold,
                self.recover_s)
        (log.warning if why == "pressure" else log.info)(msg, *args)
        cb = self.on_change
        if cb is not None:
            try:
                cb(level, LEVEL_NAMES[level])
            except Exception:
                log.exception("degradation on_change callback failed")

    def snapshot(self) -> dict:
        level = self.current_level()
        with self._lock:
            return {"level": level, "name": LEVEL_NAMES[level],
                    "peak_level": self._peak_level,
                    "transitions": self._transitions,
                    "window_sheds": len(self._sheds),
                    "window_watchdogs": len(self._watchdogs),
                    "window_burns": len(self._burns)}


# ---------------------------------------------------------------------------
# process-global install: deep layers consult/feed the ladder without a
# runtime reference (mirrors tracing's default-tracer pattern)
# ---------------------------------------------------------------------------

_installed: Optional[DegradationLadder] = None


def install(ladder: DegradationLadder) -> None:
    global _installed
    _installed = ladder


def uninstall(ladder: DegradationLadder) -> None:
    """Remove ``ladder`` if it is the installed one (a newer runtime's
    ladder is never clobbered by an older runtime's close)."""
    global _installed
    if _installed is ladder:
        _installed = None


def installed() -> Optional[DegradationLadder]:
    return _installed


def note_shed() -> None:
    ladder = _installed
    if ladder is not None:
        ladder.record_shed()


def note_watchdog() -> None:
    ladder = _installed
    if ladder is not None:
        ladder.record_watchdog()


def note_burn() -> None:
    ladder = _installed
    if ladder is not None:
        ladder.record_burn()


def gather_scale() -> float:
    """Batch-gather window multiplier for the scheduler: 1.0 at normal,
    0.0 at level >= 1 (shrink-coalesce and above dispatch per request)."""
    ladder = _installed
    if ladder is None:
        return 1.0
    return 0.0 if ladder.current_level() >= 1 else 1.0


def force_dispatch_mode() -> bool:
    """Iteration-mode override: at level >= 1 (the same threshold that
    collapses gather windows) new streams fall back from the persistent
    iteration loop to dispatch-granular batching — under pressure the
    simpler wave path sheds predictably, and recovery (hysteresis)
    re-admits iteration mode with no operator action."""
    ladder = _installed
    if ladder is None:
        return False
    return ladder.current_level() >= 1
