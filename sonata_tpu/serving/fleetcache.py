"""sonata-fleetcache: cache-affinity routing, router single-flight, and
hot-set replication over the mesh.

PR 15's synthesis cache (``serving/synthcache.py``) is strictly
per-node: behind the mesh router, least-outstanding routing sprays
identical requests across N backends, so the fleet pays up to N misses
per template and the effective hit ratio divides by fleet size.  This
module makes the cache a fleet property:

- **Cache-affinity routing.**  The router derives the PR-15 canonical
  cache key itself — every key input is in the decoded request plus the
  per-voice options it learns from ``VoiceInfo``/``SetSynthesisOptions``
  responses (:class:`VoiceKeyInfo`) — and rendezvous-hashes (HRW,
  blake2b) *cacheable* requests over the routable membership, so
  repeats of a template land on the node already holding its entry.
  The derivation is byte-identical to the node's
  (``synthcache.utterance_key`` is shared; the scales are canonicalized
  through float32, the wire precision — pinned by
  tests/test_fleetcache.py).  A **load-skew guard** keeps a hot
  template from wedging one node: when the affinity target's
  outstanding count exceeds the fleet minimum by more than
  ``SONATA_FLEETCACHE_SKEW`` slots, the request falls back to plain
  least-outstanding routing.  Non-cacheable requests (unknown voice,
  unresolvable speaker) and cache-off deployments keep PR-12 routing
  byte-for-byte.
- **Router single-flight.**  N concurrent identical requests fleet-wide
  admit ONE backend synthesis: the leader's chunks are teed through a
  router-side fill handle; followers stream from it with the PR-15
  bounded-wait / leader-failure semantics (``synthcache``'s
  ``FillHandle``/``FollowerStream`` are reused against this class —
  the router never *stores* committed streams, the backend caches do).
- **Hot-set replication.**  Each node's synthcache advertises its LRU
  head (``hot_keys`` in the scope export, scraped by the fleetscope);
  riding the per-node prober threads on its own cadence (the placement
  reconciler's anti-entropy pattern, shared via
  :class:`~.placement.ProbeCadence`), the router replays up to
  ``SONATA_FLEETCACHE_REPLICATE_K`` hot templates to the key's next
  rendezvous peer — so a SIGKILLed node's hot set survives its
  restart, and affinity failover (HRW over the remaining nodes IS the
  peer preference order) finds a warm peer instead of a cold miss.
- **Failure posture.**  The whole tier is advisory: the
  ``mesh.cache_affinity`` failpoint fires inside key derivation, and
  ANY error there (injected or real) degrades that request to plain
  least-outstanding routing — a broken affinity tier can never fail a
  request.  Replication failures are counted, never raised.
- **Observability.**  ``sonata_fleetcache_{affinity_hits,
  skew_fallbacks,replications}_total`` on the metrics plane, and a
  fleet cache rollup (fleet hit ratio, per-node affinity share,
  cache-byte totals) on ``/debug/fleet`` via the fleetscope.

Nothing here imports gRPC or jax; the replication transport is a
callable supplied by the frontend (``mesh_server``), like the
placement plane's ``apply_*`` ops.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from . import faults, synthcache
from .placement import ProbeCadence

log = logging.getLogger("sonata.serving")

FLEETCACHE_ENV = "SONATA_FLEETCACHE"
SKEW_ENV = "SONATA_FLEETCACHE_SKEW"
REPLICATE_K_ENV = "SONATA_FLEETCACHE_REPLICATE_K"

DEFAULT_SKEW = 4
#: how often (per node) the prober-riding replication pass runs; a
#: constant like the fleetscope's scrape cadence floor, not a knob —
#: replication is anti-entropy, not a latency path
DEFAULT_REPLICATE_INTERVAL_S = 2.0
#: bounded memory of key -> (rpc, encoded request) for replication
#: replay (digest keys are not invertible, so the router remembers the
#: payloads it derived keys from, LRU-bounded)
PAYLOAD_MEMORY_MAX = 512

#: fleet-cache counter families, loop-registered like the mesh
#: router's MESH_COUNTER_FAMILIES so the sonata-lint metricsdoc pass
#: resolves the names
FLEETCACHE_COUNTER_FAMILIES = (
    ("sonata_fleetcache_affinity_hits_total", "affinity_hits",
     "Cacheable requests routed to their rendezvous affinity node "
     "(repeats of a template land on the node holding its entry)."),
    ("sonata_fleetcache_skew_fallbacks_total", "skew_fallbacks",
     "Cacheable requests that fell back to least-outstanding routing "
     "because the affinity target's outstanding count exceeded the "
     "fleet minimum by more than SONATA_FLEETCACHE_SKEW slots."),
    ("sonata_fleetcache_replications_total", "replications",
     "Hot cache templates replayed to their next rendezvous peer by "
     "the prober-riding hot-set replication pass."),
)


def resolve_enabled() -> bool:
    """``SONATA_FLEETCACHE`` (the one default-defining read): 0 / unset
    / unparseable = off — the router's request path is then
    byte-for-byte the PR-12 one."""
    raw = os.environ.get(FLEETCACHE_ENV, "").strip()
    if not raw:
        return False
    try:
        return int(raw) != 0
    except ValueError:
        log.warning("ignoring non-numeric %s=%r (fleetcache stays off)",
                    FLEETCACHE_ENV, raw)
        return False


def resolve_skew() -> int:
    """``SONATA_FLEETCACHE_SKEW``: how many outstanding slots above the
    fleet minimum the affinity target may carry before a cacheable
    request falls back to least-outstanding routing."""
    try:
        return max(0, int(os.environ.get(SKEW_ENV, DEFAULT_SKEW)))
    except ValueError:
        return DEFAULT_SKEW


def resolve_replicate_k() -> int:
    """``SONATA_FLEETCACHE_REPLICATE_K``: how many LRU-head templates
    per node the replication pass keeps warm on the next rendezvous
    peer.  0 / unset = replication off (affinity + single-flight still
    run)."""
    try:
        return max(0, int(os.environ.get(REPLICATE_K_ENV, "0")))
    except ValueError:
        return 0


def hrw_score(key: str, addr: str) -> int:
    """Rendezvous (highest-random-weight) score of ``addr`` for
    ``key``: a blake2b draw, not Python ``hash()`` — every router in a
    fleet must agree on the preference order, across processes and
    restarts.  Hashed over the node's configured ``host:port`` (stable
    for the router's lifetime), never the scraped node id (which
    mutates when a probe learns the backend's real id)."""
    blob = f"{key}\x1f{addr}".encode("utf-8")
    return int.from_bytes(
        hashlib.blake2b(blob, digest_size=8).digest(), "big")


class VoiceKeyInfo:
    """The per-voice half of the cache-key derivation, learned from the
    wire: current speaker (resolved to its id like the node resolves
    it), scales at wire (float32) precision, and the output audio
    format.  ``cacheable`` is False when the router could not resolve
    the speaker name — such a voice routes PR-12 style rather than risk
    a key that disagrees with the node's."""

    __slots__ = ("voice_id", "speaker", "length_scale", "noise_scale",
                 "noise_w", "sample_rate", "sample_width", "channels",
                 "name_to_id", "cacheable")

    def __init__(self, voice_id: str):
        self.voice_id = voice_id
        self.speaker: Optional[int] = None
        self.length_scale = 1.0
        self.noise_scale = 0.667
        self.noise_w = 0.8
        self.sample_rate = 0
        self.sample_width = 0
        self.channels = 0
        #: speaker name -> id, inverted from the wire's id -> name map
        self.name_to_id: Dict[str, int] = {}
        self.cacheable = True

    def resolve_speaker(self, name: Optional[str]) -> None:
        """Mirror the node's ``SetSynthesisOptions`` resolution: map
        name -> id, fall back to a literal numeric name, and mark the
        voice non-cacheable when neither works (the node knows speakers
        the wire map does not; guessing would split identity)."""
        if not name:
            self.speaker = None
            self.cacheable = True
            return
        sid = self.name_to_id.get(name)
        if sid is None and name.isdigit():
            sid = int(name)
        self.speaker = sid
        self.cacheable = sid is not None


class FleetCache:
    """The router-side fleet cache tier over a
    :class:`~sonata_tpu.serving.mesh.MeshRouter`.

    Lock discipline: :meth:`affinity_choice_locked` runs under the
    ROUTER lock (called from ``pick``); this class's own lock is a leaf
    — it is never held while acquiring the router lock, so the nesting
    order router -> fleetcache can never invert."""

    def __init__(self, router, *, fleet=None,
                 skew: Optional[int] = None,
                 replicate_k: Optional[int] = None,
                 replicate_interval_s: float = DEFAULT_REPLICATE_INTERVAL_S,
                 wait_s: Optional[float] = None,
                 clock=None):
        self.router = router
        #: the fleetscope (scrape plane) — where node hot-set
        #: advertisements come from; None disables replication only
        self.fleet = fleet
        self.skew = (skew if skew is not None else resolve_skew())
        self.replicate_k = (replicate_k if replicate_k is not None
                            else resolve_replicate_k())
        self.wait_s = (wait_s if wait_s is not None
                       else synthcache.resolve_wait_s())
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._closed = False
        #: voice_id -> VoiceKeyInfo (the wire-learned key inputs)
        self._voices: Dict[str, VoiceKeyInfo] = {}
        #: router-side single-flight: key -> the entry a leader fills
        self._flight: Dict[str, synthcache._Entry] = {}
        #: key -> (rpc name, encoded request) for replication replay,
        #: LRU-bounded at PAYLOAD_MEMORY_MAX
        self._payloads: "OrderedDict[str, tuple]" = OrderedDict()
        #: key -> addr it was last replicated to (re-replicated when
        #: the rendezvous target moves after membership change)
        self._replicated: Dict[str, str] = {}
        #: addr -> cacheable requests affinity-routed there
        self._affinity_share: Dict[str, int] = {}
        self._cadence = ProbeCadence(replicate_interval_s,
                                     clock=self._clock)
        self._transport: Optional[Callable] = None
        self.stats = {"affinity_hits": 0, "skew_fallbacks": 0,
                      "replications": 0, "replication_failures": 0,
                      "affinity_errors": 0, "uncacheable": 0,
                      "singleflight_leads": 0, "singleflight_follows": 0,
                      "follower_hits": 0, "follower_fallbacks": 0}

    # -- voice registry (the wire-learned key inputs) --------------------------
    def learn_voice(self, info) -> None:
        """Record a voice's key inputs from a ``VoiceInfo`` response
        (LoadVoice fan-out, placement replay).  Duck-typed on the
        message object so this module never imports the codec."""
        try:
            opts, audio = info.synth_options, info.audio
            if not info.voice_id or opts is None or audio is None:
                return
            vki = VoiceKeyInfo(info.voice_id)
            vki.name_to_id = {name: int(sid) for sid, name
                              in (info.speakers or {}).items()}
            vki.length_scale = float(opts.length_scale)
            vki.noise_scale = float(opts.noise_scale)
            vki.noise_w = float(opts.noise_w)
            vki.sample_rate = int(audio.sample_rate)
            vki.sample_width = int(audio.sample_width)
            vki.channels = int(audio.num_channels)
            vki.resolve_speaker(opts.speaker or None)
            with self._lock:
                self._voices[info.voice_id] = vki
        except Exception:
            log.debug("fleetcache: unusable VoiceInfo ignored",
                      exc_info=True)

    def update_options(self, voice_id: str, opts) -> None:
        """Fold a node-resolved ``SetSynthesisOptions`` response (the
        full post-update option set) into the voice's record."""
        try:
            with self._lock:
                vki = self._voices.get(voice_id)
            if vki is None or opts is None:
                return
            vki.length_scale = float(opts.length_scale)
            vki.noise_scale = float(opts.noise_scale)
            vki.noise_w = float(opts.noise_w)
            vki.resolve_speaker(opts.speaker or None)
        except Exception:
            log.debug("fleetcache: unusable SynthesisOptions ignored",
                      exc_info=True)

    def forget_voice(self, voice_id: str) -> None:
        with self._lock:
            self._voices.pop(voice_id, None)

    # -- key derivation + affinity choice --------------------------------------
    def routing_key(self, kind: str, request) -> Optional[str]:
        """The router-derived cache key for one decoded request, or
        None when the request is not cacheable (unknown voice,
        unresolvable speaker) — None keeps PR-12 routing byte-for-byte.
        Fires the ``mesh.cache_affinity`` failpoint; ANY error (injected
        or real) degrades to None — a broken affinity tier can never
        fail a request."""
        try:
            faults.fire("mesh.cache_affinity")
            with self._lock:
                vki = self._voices.get(request.voice_id or "")
            if vki is None or not vki.cacheable:
                with self._lock:
                    self.stats["uncacheable"] += 1
                return None
            return synthcache.utterance_key(
                kind, request, voice_id=vki.voice_id,
                speaker=vki.speaker, length_scale=vki.length_scale,
                noise_scale=vki.noise_scale, noise_w=vki.noise_w,
                sample_rate=vki.sample_rate,
                sample_width=vki.sample_width, channels=vki.channels)
        except Exception:
            with self._lock:
                self.stats["affinity_errors"] += 1
            log.debug("fleetcache: key derivation degraded to "
                      "least-outstanding routing", exc_info=True)
            return None

    def affinity_choice_locked(self, key: str, routable: list):
        """The rendezvous owner of ``key`` among ``routable`` (CLOSED,
        healthy nodes — the caller's candidate list), or None to fall
        back to least-outstanding: skew guard tripped, empty list, or
        any internal error.  Runs under the router lock."""
        try:
            if not routable:
                return None
            owner = max(routable,
                        key=lambda n: hrw_score(key, n.spec.addr))
            floor = min(n.outstanding for n in routable)
            if owner.outstanding - floor > self.skew:
                with self._lock:
                    self.stats["skew_fallbacks"] += 1
                return None
            with self._lock:
                self.stats["affinity_hits"] += 1
                self._affinity_share[owner.spec.addr] = \
                    self._affinity_share.get(owner.spec.addr, 0) + 1
            return owner
        except Exception:
            with self._lock:
                self.stats["affinity_errors"] += 1
            log.debug("fleetcache: affinity pick degraded",
                      exc_info=True)
            return None

    # -- router-side single-flight ---------------------------------------------
    def begin_stream(self, key: Optional[str]):
        """Single-flight admission for one cacheable request.  Returns
        ``("fill", FillHandle)`` for the leader (tee every forwarded
        chunk in; commit on clean completion, abort on any other exit),
        ``("follow", FollowerStream)`` when an identical request is in
        flight (PR-15 bounded-wait / leader-failure semantics), or
        ``("bypass", None)``.  Unlike the node cache there is no
        committed store: a commit just releases the followers — the
        backend caches hold the streams."""
        if key is None:
            return ("bypass", None)
        with self._lock:
            if self._closed:
                return ("bypass", None)
            entry = self._flight.get(key)
            if entry is not None:
                self.stats["singleflight_follows"] += 1
                return ("follow",
                        synthcache.FollowerStream(self, entry,
                                                  self.wait_s))
            entry = synthcache._Entry(key)
            self._flight[key] = entry
            self.stats["singleflight_leads"] += 1
            return ("fill", synthcache.FillHandle(self, entry))

    # FillHandle/FollowerStream owner surface (duck-typed SynthCache)
    def _commit(self, entry) -> None:
        with self._lock:
            self._flight.pop(entry.key, None)
        with entry.cond:
            entry.state = synthcache._COMPLETE
            entry.cond.notify_all()

    def _abort(self, entry) -> None:
        with self._lock:
            self._flight.pop(entry.key, None)
        with entry.cond:
            entry.state = synthcache._FAILED
            entry.cond.notify_all()

    def _note_follower(self, hit: bool) -> None:
        with self._lock:
            self.stats["follower_hits" if hit
                       else "follower_fallbacks"] += 1

    # -- hot-set replication ---------------------------------------------------
    def set_replicate_transport(self, fn: Callable) -> None:
        """``fn(node, rpc_name, payload, key)`` replays one encoded
        request against ``node`` and drains the response stream (the
        frontend supplies real gRPC; tests supply fakes)."""
        self._transport = fn

    def note_payload(self, key: Optional[str], rpc_name: str,
                     payload: bytes) -> None:
        """Remember the encoded request behind ``key`` so the
        replication pass can replay it (keys are digests — not
        invertible).  LRU-bounded; eviction forgets the replication
        memory too so a re-hot key re-replicates."""
        if key is None:
            return
        with self._lock:
            self._payloads[key] = (rpc_name, payload)
            self._payloads.move_to_end(key)
            while len(self._payloads) > PAYLOAD_MEMORY_MAX:
                old, _ = self._payloads.popitem(last=False)
                self._replicated.pop(old, None)

    def on_probe_cycle(self, node) -> None:
        """Called by the router's prober after every health cycle; runs
        one replication pass for ``node`` on the slower cadence."""
        if (self.replicate_k <= 0 or self._transport is None
                or self.fleet is None or self._closed):
            return
        if self._cadence.due(node.index):
            self.replicate_for_node(node)

    def replicate_for_node(self, node) -> None:
        """Keep ``node``'s advertised hot set warm on each key's next
        rendezvous peer: at most ONE replay per cycle (anti-entropy,
        not a bulk copy — the placement reconciler's pacing).  Only
        keys ``node`` actually OWNS (HRW-max among routable) are
        pushed; the peer for a key is the first routable node after
        ``node`` in the key's HRW preference order — exactly where
        affinity failover lands when ``node`` dies."""
        try:
            view = self.fleet.node_cache_view(node)
            hot = (view or {}).get("hot_keys") or ()
            if not hot:
                return
            routable = self.router.routable_nodes()
            peers = [n for n in routable
                     if n.spec.addr != node.spec.addr]
            if not peers:
                return
            for key in hot[: self.replicate_k]:
                owner = max(routable,
                            key=lambda n: hrw_score(key, n.spec.addr))
                if owner.spec.addr != node.spec.addr:
                    # a key this node merely RECEIVED (by replication
                    # or skew spillover) — replicating it onward would
                    # ping-pong the copy between holders every cycle
                    # and starve the keys this node actually owns
                    continue
                target = max(peers,
                             key=lambda n: hrw_score(key, n.spec.addr))
                with self._lock:
                    if self._replicated.get(key) == target.spec.addr:
                        continue
                    payload = self._payloads.get(key)
                if payload is None:
                    continue
                rpc_name, body = payload
                try:
                    self._transport(target, rpc_name, body, key)
                    with self._lock:
                        self.stats["replications"] += 1
                        self._replicated[key] = target.spec.addr
                    log.debug(
                        "fleetcache: replicated hot entry %s from node "
                        "%s to %s", key[:12], node.node_id,
                        target.node_id)
                except Exception as e:
                    with self._lock:
                        self.stats["replication_failures"] += 1
                    log.debug("fleetcache: replication of %s to %s "
                              "failed: %s", key[:12], target.node_id, e)
                return  # one replay per cycle
        except Exception:
            log.debug("fleetcache: replication pass skipped",
                      exc_info=True)

    # -- introspection / metrics -----------------------------------------------
    def stat(self, name: str) -> float:
        with self._lock:
            return float(self.stats[name])

    def snapshot(self) -> dict:
        """One view for ``/debug/fleet``'s cache section."""
        with self._lock:
            return {"skew": self.skew,
                    "replicate_k": self.replicate_k,
                    "stats": dict(self.stats),
                    "affinity_share": dict(self._affinity_share),
                    "voices": sorted(self._voices),
                    "in_flight": len(self._flight),
                    "payload_memory": len(self._payloads)}

    def bind_metrics(self, registry) -> None:
        """Attach the fleet-cache counters as scrape-time callbacks.
        Unlabeled and process-lifetime (the failpoint-counter idiom) —
        no per-node teardown to record."""
        for name, key, help_text in FLEETCACHE_COUNTER_FAMILIES:
            registry.counter(name, help_text).set_function(
                lambda k=key: self.stat(k))

    def close(self) -> None:
        """Refuse new single-flight admissions and fail the entries in
        flight (their leaders' own streams finish through the
        transport; followers fall back or fail typed)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            doomed = list(self._flight.values())
            self._flight.clear()
        for entry in doomed:
            with entry.cond:
                if entry.state == synthcache._FILLING:
                    entry.state = synthcache._FAILED
                entry.cond.notify_all()
