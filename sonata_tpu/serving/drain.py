"""Graceful drain: make a rolling restart a non-event.

A process that receives SIGTERM used to simply vanish mid-stream — the
only "drain logic" was a comment in the gRPC frontend's shutdown path.
At fleet scale every deploy was therefore a dropped-request event: the
load balancer kept routing to a replica that was already dying, and
every stream it was serving broke.

This module is the state machine both frontends drain through:

- :class:`Draining` — the **typed** refusal for work arriving during a
  drain.  It maps to gRPC ``UNAVAILABLE`` (with a ``draining`` detail),
  deliberately *not* ``RESOURCE_EXHAUSTED``: clients, the degradation
  ladder, and dashboards must be able to tell a deploy from overload
  (a shed is pressure; a drain is routine).
- :class:`DrainCoordinator` — one per process (owned by
  :class:`~sonata_tpu.serving.ServingRuntime`), holding the drain flag,
  the per-phase structured log lines, and the bounded wait for in-flight
  work.  The pinned phase order is :data:`DRAIN_PHASES`:

  1. ``readiness-off`` — every readiness gate flips *first*, so the
     balancer stops routing here before anything else changes;
  2. ``reject-admissions`` — new requests fail fast with
     :class:`Draining` (in-flight ones are untouched);
  3. ``wait-in-flight`` — in-flight streams and queued scheduler
     dispatches finish, bounded by ``SONATA_DRAIN_TIMEOUT_S``;
  4. ``voices`` — replica pools → schedulers → models tear down
     (the pool refuses breaker resubmission and half-open probes
     *typed* once it is draining — no work re-enters a closing
     scheduler, no probe builds a worker thread nobody will join);
  5. ``runtime`` — tracer/scope, then the metrics plane;
  6. ``done``.

The ``sonata_draining`` gauge mirrors the flag on the scrape plane, so
a dashboard can overlay deploys on every other signal.  Size the
orchestrator's ``terminationGracePeriodSeconds`` *above*
``SONATA_DRAIN_TIMEOUT_S`` (docs/DEPLOY.md "Rolling restarts") or the
kernel's SIGKILL wins the race this module exists to lose gracefully.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from ..core import OperationError

log = logging.getLogger("sonata.serving")

DRAIN_TIMEOUT_ENV = "SONATA_DRAIN_TIMEOUT_S"
DEFAULT_DRAIN_TIMEOUT_S = 30.0

#: the pinned shutdown order; every phase logs exactly one structured
#: line (``drain: phase=<name> ...``) so an operator can read a restart
#: end to end from the log stream, and the chaos smoke can assert the
#: order never regresses
DRAIN_PHASES = ("readiness-off", "reject-admissions", "wait-in-flight",
                "voices", "runtime", "done")

#: how often the wait-in-flight phase re-checks the idle predicate
_IDLE_POLL_S = 0.02


class Draining(OperationError):
    """New work refused because the process is draining for a restart.

    Typed (and mapped to gRPC ``UNAVAILABLE``) so callers can tell a
    routine deploy from overload: a client retries against another
    replica immediately; the degradation ladder does **not** count it
    as shed pressure."""


def resolve_drain_timeout_s(timeout_s: Optional[float] = None) -> float:
    """Explicit arg > ``SONATA_DRAIN_TIMEOUT_S`` > 30 s."""
    if timeout_s is not None:
        return max(0.0, float(timeout_s))
    try:
        return max(0.0, float(os.environ.get(DRAIN_TIMEOUT_ENV,
                                             DEFAULT_DRAIN_TIMEOUT_S)))
    except ValueError:
        return DEFAULT_DRAIN_TIMEOUT_S


class DrainCoordinator:
    """Process drain state: flag, phase log, bounded in-flight wait.

    The flag is sticky — a drain never un-happens — and ``begin`` is
    first-caller-wins, so a second SIGTERM (or a drain racing an
    explicit shutdown) is a no-op rather than a second teardown.
    """

    def __init__(self, *, timeout_s: Optional[float] = None):
        self.timeout_s = resolve_drain_timeout_s(timeout_s)
        self._lock = threading.Lock()
        self._draining = threading.Event()
        self._reason: Optional[str] = None
        self._started_at: Optional[float] = None
        #: (phase, monotonic seconds since begin) in emission order
        self.phases: list = []

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def reason(self) -> Optional[str]:
        with self._lock:
            return self._reason

    def begin(self, reason: str = "shutdown") -> bool:
        """Enter the drain state.  Returns True for the first caller
        (who owns running the phases), False for everyone after."""
        with self._lock:
            if self._draining.is_set():
                return False
            self._reason = reason
            self._started_at = time.monotonic()
            self._draining.set()
        return True

    def raise_if_draining(self) -> None:
        """Admission-path hook: typed refusal for new work mid-drain."""
        if self._draining.is_set():
            raise Draining(
                f"draining: server is shutting down for a restart "
                f"({self.reason}); retry against another replica")

    def note_phase(self, phase: str, **fields) -> None:
        """One structured log line per phase, in :data:`DRAIN_PHASES`
        order (the order itself is the caller's contract — this method
        just records and logs)."""
        started = self._started_at
        elapsed_ms = (round((time.monotonic() - started) * 1e3, 1)
                      if started is not None else 0.0)
        with self._lock:
            self.phases.append((phase, elapsed_ms))
        detail = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        log.warning("drain: phase=%s elapsed_ms=%s reason=%s%s",
                    phase, elapsed_ms, self._reason,
                    f" {detail}" if detail else "")

    def wait_idle(self, idle: Callable[[], bool],
                  timeout_s: Optional[float] = None) -> bool:
        """Poll ``idle()`` until it holds or the drain budget expires.

        Returns True when the process went idle inside the budget,
        False on expiry (the caller proceeds to teardown regardless —
        stragglers fail typed when their scheduler shuts down, which
        beats being SIGKILLed mid-dispatch by the orchestrator)."""
        budget = self.timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + budget
        while True:
            try:
                if idle():
                    return True
            except Exception:
                # a health probe racing teardown must not abort the
                # drain: treat an unreadable predicate as not-idle
                log.exception("drain idle predicate failed; retrying")
            if time.monotonic() >= deadline:
                return False
            time.sleep(_IDLE_POLL_S)
