"""Request-scoped tracing: span pipeline, dispatch attribution, capture.

The serving plane so far exposes only *aggregate* signals — RTF counters,
TTFB/latency histograms, shed/expired counters.  When one stream's TTFB
blows past p99 those cannot say whether the time went to queue wait,
coalescer gather, a cold bucket compile, a breaker-driven resubmission,
or the decode itself.  This module is the Dapper-style answer (Sigelman
et al., 2010): every request carries a ``request_id`` (accepted from gRPC
metadata ``x-request-id`` or generated) and grows a span tree across the
pipeline — text-normalize → phonemize → encode-ids → admission →
queue-wait → dispatch → decode → postprocess → stream-emit.

Design constraints, in order:

- **Lock-cheap and always-on-capable.**  A span is a monotonic-clock pair
  plus a dict; recording appends to a per-trace list under a per-trace
  lock.  Every hook is a no-op (one contextvar read) when no trace is
  active, so library code can be instrumented unconditionally.
- **Cross-thread by construction.**  The pipeline hops threads (gRPC
  handler → scheduler worker → coalescer/finisher), so context does not
  travel implicitly: the scheduler captures ``current()`` at submit time
  and records queue-wait/dispatch spans into each item's trace from its
  worker thread.
- **Dispatch attribution** (the Orca lesson, Yu et al., OSDI '22): a
  coalesced device dispatch is ONE shared span recorded into every
  participating request's trace — same ``dispatch_id``, annotated with
  batch size, the co-batched peers' request ids, bucket shape, padding
  ratio, replica/device, and compile-vs-cached.  The model layer fills
  the bucket/compile fields through :func:`annotate_dispatch`, a
  contextvar channel the scheduler opens around ``speak_batch`` — no
  tracer object ever threads through the model protocol.

Finished traces export three ways:

1. structured JSON log lines when ``SONATA_TRACE_LOG`` is set (truthy =
   via the ``sonata.trace`` logger; a path = appended as JSONL);
2. Chrome trace-event / Perfetto-loadable JSON
   (:meth:`Tracer.chrome_trace`, served at ``/debug/traces?format=chrome``);
3. bounded ring buffers of the N most recent and N slowest traces
   (``SONATA_TRACE_RECENT``/``SONATA_TRACE_SLOWEST``), served from the
   metrics HTTP plane at ``/debug/traces`` and ``/debug/slowest``.

``SONATA_TRACE=0`` disables tracing entirely (default: on; measured
overhead on the streaming bench is within noise — see
BENCH_STREAMING_CPU_r09.json ``trace_overhead``).
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import itertools
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Iterator, Optional

log = logging.getLogger("sonata.trace")

TRACE_ENV = "SONATA_TRACE"
TRACE_LOG_ENV = "SONATA_TRACE_LOG"
TRACE_RECENT_ENV = "SONATA_TRACE_RECENT"
TRACE_SLOWEST_ENV = "SONATA_TRACE_SLOWEST"
REQUEST_ID_METADATA_KEY = "x-request-id"
DEFAULT_RECENT = 64
DEFAULT_SLOWEST = 32

#: monotonic → wall-clock anchor, fixed at import so every span in a
#: process shares one timebase (Chrome trace ``ts`` must be comparable
#: across traces)
_WALL_ANCHOR = time.time() - time.monotonic()

_ids = itertools.count(1)


def new_id() -> str:
    """Process-unique short id (span/dispatch ids)."""
    return f"{next(_ids):x}"


def new_request_id() -> str:
    """Generated request id for requests that arrived without one."""
    return uuid.uuid4().hex[:16]


def request_id_from_metadata(metadata) -> Optional[str]:
    """Extract ``x-request-id`` from gRPC invocation metadata (a sequence
    of (key, value) pairs), or None."""
    for key, value in metadata or ():
        if str(key).lower() == REQUEST_ID_METADATA_KEY and value:
            return str(value)
    return None


def request_id_from_context(context) -> Optional[str]:
    """``x-request-id`` from a gRPC ServicerContext (or test double)."""
    meta = getattr(context, "invocation_metadata", None)
    if meta is None:
        return None
    try:
        return request_id_from_metadata(meta())
    except Exception:
        return None


#: the one definition of "this env knob is off" (SONATA_TRACE and the
#: SONATA_TRACE_LOG sink check must never diverge on it)
_FALSY = ("0", "false", "off", "no")


def _env_truthy(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    return raw.strip().lower() not in _FALSY


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


class _NullSpan:
    """Annotation sink for instrumented code running without a trace."""

    __slots__ = ()
    span_id = None

    def annotate(self, **attrs) -> None:
        pass

    def finish(self, end: Optional[float] = None) -> None:
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One timed stage of a request; belongs to exactly one trace."""

    __slots__ = ("span_id", "parent_id", "name", "start", "end", "attrs")

    def __init__(self, name: str, parent_id: Optional[str],
                 start: Optional[float] = None, attrs: Optional[dict] = None):
        self.span_id = new_id()
        self.parent_id = parent_id
        self.name = name
        self.start = time.monotonic() if start is None else start
        self.end: Optional[float] = None
        self.attrs = dict(attrs) if attrs else {}

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def finish(self, end: Optional[float] = None) -> None:
        if self.end is None:
            self.end = time.monotonic() if end is None else end

    @property
    def duration_s(self) -> Optional[float]:
        if self.end is None:
            return None
        return self.end - self.start

    def to_dict(self, t0: float) -> dict:
        """Serializable view; times relative to the trace root (ms)."""
        d = {"span_id": self.span_id, "parent_id": self.parent_id,
             "name": self.name,
             "start_ms": round((self.start - t0) * 1e3, 3)}
        if self.end is not None:
            d["duration_ms"] = round((self.end - self.start) * 1e3, 3)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class Trace:
    """One request's span tree.  Spans may be recorded from any thread."""

    def __init__(self, tracer: "Tracer", name: str, request_id: str,
                 attrs: Optional[dict] = None):
        self._tracer = tracer
        self.name = name
        self.request_id = request_id
        self.attrs = dict(attrs) if attrs else {}
        self.status: Optional[str] = None
        self.wall_start = time.time()
        self._lock = threading.Lock()
        self.root = Span(name, parent_id=None)
        self._spans = [self.root]
        self._finished = False

    # -- recording -----------------------------------------------------------
    def new_span(self, name: str, parent=None,
                 start: Optional[float] = None, end: Optional[float] = None,
                 attrs: Optional[dict] = None) -> Span:
        """Record a span; ``parent`` is a Span, a span id, or None (root).
        Pass ``end`` to record an already-finished interval (how the
        scheduler backfills queue-wait/dispatch from its worker thread)."""
        parent_id = (parent.span_id if isinstance(parent, Span)
                     else parent) or self.root.span_id
        span = Span(name, parent_id, start=start, attrs=attrs)
        if end is not None:
            span.finish(end)
        with self._lock:
            self._spans.append(span)
        return span

    def annotate(self, **attrs) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def finish(self, status: str = "ok") -> None:
        """Idempotent; hands the trace to the tracer's ring buffers."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.status = status
        self.root.finish()
        self._tracer._record(self)

    # -- views ---------------------------------------------------------------
    @property
    def duration_s(self) -> float:
        end = self.root.end if self.root.end is not None else time.monotonic()
        return end - self.root.start

    def spans_snapshot(self) -> list:
        with self._lock:
            return list(self._spans)

    def span_names(self) -> set:
        return {s.name for s in self.spans_snapshot()}

    def to_dict(self) -> dict:
        t0 = self.root.start
        with self._lock:
            spans = list(self._spans)
            attrs = dict(self.attrs)
        return {"request_id": self.request_id, "name": self.name,
                "status": self.status, "wall_start": self.wall_start,
                "duration_ms": round(self.duration_s * 1e3, 3),
                "attrs": attrs,
                "spans": [s.to_dict(t0) for s in spans]}

    def chrome_events(self, tid: int, pid: int = 1) -> list:
        """Chrome trace-event ``X`` (complete) events, one per finished
        span, on one virtual thread per request."""
        events = [{"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                   "args": {"name": f"req {self.request_id}"}}]
        for s in self.spans_snapshot():
            end = s.end if s.end is not None else s.start
            events.append({
                "ph": "X", "pid": pid, "tid": tid, "name": s.name,
                "cat": self.name,
                "ts": round((s.start + _WALL_ANCHOR) * 1e6, 1),
                "dur": round((end - s.start) * 1e6, 1),
                "args": {**s.attrs, "request_id": self.request_id,
                         "span_id": s.span_id,
                         "parent_id": s.parent_id or ""},
            })
        return events


def chrome_events_from_dict(trace_dict: dict, *, pid: int, tid: int = 1,
                            wall_offset_s: float = 0.0) -> list:
    """Chrome trace events from a *serialized* :meth:`Trace.to_dict`
    document — how the sonata-mesh router splices a remote node's trace
    (fetched as JSON over the node's ``/debug/traces?id=`` plane) into
    one stitched cross-host document.

    ``wall_offset_s`` is the probe-measured remote-minus-local wall
    clock offset; subtracting it re-bases the remote spans onto the
    local timebase, matching :meth:`Trace.chrome_events`'s
    wall-anchored ``ts`` so router and node spans line up in one
    Perfetto load."""
    rid = trace_dict.get("request_id", "")
    events = [{"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
               "args": {"name": f"req {rid}"}}]
    t0 = float(trace_dict.get("wall_start", 0.0)) - wall_offset_s
    for s in trace_dict.get("spans", ()):
        start_s = t0 + float(s.get("start_ms", 0.0)) / 1e3
        events.append({
            "ph": "X", "pid": pid, "tid": tid,
            "name": s.get("name", "?"),
            "cat": trace_dict.get("name", ""),
            "ts": round(start_s * 1e6, 1),
            "dur": round(float(s.get("duration_ms", 0.0)) * 1e3, 1),
            "args": {**(s.get("attrs") or {}), "request_id": rid,
                     "span_id": s.get("span_id", ""),
                     "parent_id": s.get("parent_id") or ""},
        })
    return events


# ---------------------------------------------------------------------------
# context propagation (same-thread hooks)
# ---------------------------------------------------------------------------

#: (trace, current_span) for the executing context, or None
_CTX: "contextvars.ContextVar[Optional[tuple]]" = contextvars.ContextVar(
    "sonata_trace_ctx", default=None)


def current() -> Optional[tuple]:
    """The active (trace, span) pair, or None.  What cross-thread stages
    (scheduler items, stream producers) capture at hand-off time."""
    return _CTX.get()


def current_trace() -> Optional[Trace]:
    ctx = _CTX.get()
    return ctx[0] if ctx else None


@contextlib.contextmanager
def use_trace(trace: Optional[Trace], span: Optional[Span] = None
              ) -> Iterator[Optional[Trace]]:
    """Activate ``trace`` (at ``span``, default root) for the block.
    ``trace=None`` is a no-op — callers never need to branch."""
    if trace is None:
        yield None
        return
    token = _CTX.set((trace, span if span is not None else trace.root))
    try:
        yield trace
    finally:
        _reset(token)


def _reset(token) -> None:
    """Reset a context token, tolerating cross-context finalization (a
    generator holding the block can be closed by GC on another thread,
    where the token is foreign and reset() raises ValueError)."""
    try:
        _CTX.reset(token)
    except ValueError:
        pass


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator:
    """Record a child span of the current context; no-op without a trace.

    Yields the :class:`Span` (or :data:`NULL_SPAN`), so callers can
    ``sp.annotate(...)`` unconditionally.  An escaping exception is
    recorded as an ``error`` attribute before re-raising.
    """
    ctx = _CTX.get()
    if ctx is None:
        yield NULL_SPAN
        return
    trace, parent = ctx
    sp = trace.new_span(name, parent=parent, attrs=attrs)
    token = _CTX.set((trace, sp))
    try:
        yield sp
    except BaseException as e:
        sp.annotate(error=f"{type(e).__name__}: {e}")
        raise
    finally:
        _reset(token)
        sp.finish()


# ---------------------------------------------------------------------------
# dispatch attribution channel (scheduler ↔ model)
# ---------------------------------------------------------------------------

_DISPATCH: "contextvars.ContextVar[Optional[dict]]" = contextvars.ContextVar(
    "sonata_dispatch_attrs", default=None)


@contextlib.contextmanager
def dispatch_scope(attrs: dict) -> Iterator[dict]:
    """Open the annotation channel for one device dispatch.  The
    scheduler wraps ``model.speak_batch`` in this; the model fills in
    bucket shape / padding / compile state via :func:`annotate_dispatch`
    without knowing anything about tracing."""
    token = _DISPATCH.set(attrs)
    try:
        yield attrs
    finally:
        _DISPATCH.reset(token)


def annotate_dispatch(**attrs) -> None:
    """Attach attributes to the active dispatch span, if any (no-op
    outside a :func:`dispatch_scope` — e.g. direct ``speak_batch``
    calls)."""
    d = _DISPATCH.get()
    if d is not None:
        d.update(attrs)


def annotate_dispatch_group(**attrs) -> None:
    """Like :func:`annotate_dispatch`, for models whose one
    ``speak_batch`` call issues SEVERAL device programs (bucket groups).

    Each call appends the group's attrs to ``device_groups``; the span's
    headline fields keep the first group's shape but aggregate the
    outlier-relevant ones worst-case — ``compile`` is ``cold`` if ANY
    group compiled, ``padding_ratio`` is the max — so a cold first group
    followed by cached ones can never be misread as a cached dispatch.
    """
    d = _DISPATCH.get()
    if d is None:
        return
    groups = d.setdefault("device_groups", [])
    groups.append(dict(attrs))
    if len(groups) == 1:
        d.update(attrs)
        return
    if attrs.get("compile") == "cold":
        d["compile"] = "cold"
    if "padding_ratio" in attrs:
        d["padding_ratio"] = max(d.get("padding_ratio", 0.0),
                                 attrs["padding_ratio"])
    if attrs.get("scaled"):
        # any scaled group puts the whole dispatch outside the warmup
        # lattice's coverage promise (cold-compile containment skips it)
        d["scaled"] = True


# ---------------------------------------------------------------------------
# finished-trace observer (the scope aggregation plane's feed)
# ---------------------------------------------------------------------------

#: one process-wide hook called with every finished Trace (whatever
#: tracer finished it, so injected test tracers feed the same plane).
#: None (the default) keeps trace finish exactly as cheap as before —
#: a single module-global read.
_TRACE_OBSERVER: Optional[callable] = None


def set_trace_observer(fn) -> None:
    """Install (or clear, with None) the finished-trace hook.  What
    :mod:`.scope` uses to feed per-stage quantile sketches without the
    tracer knowing the aggregation plane exists."""
    global _TRACE_OBSERVER
    _TRACE_OBSERVER = fn


# ---------------------------------------------------------------------------
# tracer: ring buffers + exports
# ---------------------------------------------------------------------------

class Tracer:
    """Owns finished-trace retention and export; cheap to share.

    ``enabled=False`` (or ``SONATA_TRACE=0``) turns :meth:`start_trace`
    into a None factory — every downstream hook then no-ops.
    """

    def __init__(self, *, enabled: Optional[bool] = None,
                 recent: Optional[int] = None,
                 slowest: Optional[int] = None,
                 log_sink: Optional[str] = None):
        self.enabled = (_env_truthy(TRACE_ENV, True)
                        if enabled is None else enabled)
        self.recent_cap = recent or _env_int(TRACE_RECENT_ENV,
                                             DEFAULT_RECENT)
        self.slowest_cap = slowest or _env_int(TRACE_SLOWEST_ENV,
                                               DEFAULT_SLOWEST)
        self._recent: "deque[Trace]" = deque(maxlen=self.recent_cap)
        self._slow: list = []  # min-heap of (duration, seq, trace)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        #: SONATA_TRACE_LOG: truthy → JSON line per trace via the
        #: ``sonata.trace`` logger; a path-looking value → append JSONL
        raw = (os.environ.get(TRACE_LOG_ENV, "")
               if log_sink is None else log_sink).strip()
        self._log_path: Optional[str] = None
        self._log_lock = threading.Lock()  # file appends only: disk I/O
        #                must never block the ring buffers or /debug reads
        self._log_lines = False
        if raw and raw.lower() not in _FALSY:
            if os.sep in raw or raw.endswith((".jsonl", ".json", ".log")):
                self._log_path = raw
            else:
                self._log_lines = True

    # -- trace lifecycle -----------------------------------------------------
    def start_trace(self, name: str, request_id: Optional[str] = None,
                    **attrs) -> Optional[Trace]:
        if not self.enabled:
            return None
        return Trace(self, name, request_id or new_request_id(), attrs)

    @contextlib.contextmanager
    def trace_request(self, name: str, request_id: Optional[str] = None,
                      **attrs) -> Iterator[Optional[Trace]]:
        """Create + activate a trace for the block; finishes it with
        ``ok`` or ``error: <type>`` (exceptions re-raise)."""
        trace = self.start_trace(name, request_id=request_id, **attrs)
        if trace is None:
            yield None
            return
        with use_trace(trace):
            try:
                yield trace
            except BaseException as e:
                trace.annotate(error=str(e))
                trace.finish(status=f"error: {type(e).__name__}")
                raise
            else:
                trace.finish("ok")

    def _record(self, trace: Trace) -> None:
        duration = trace.duration_s
        with self._lock:
            self._recent.append(trace)
            entry = (duration, next(self._seq), trace)
            if len(self._slow) < self.slowest_cap:
                heapq.heappush(self._slow, entry)
            elif duration > self._slow[0][0]:
                heapq.heapreplace(self._slow, entry)
        if self._log_lines or self._log_path:
            self._export_log_line(trace)
        observer = _TRACE_OBSERVER
        if observer is not None:
            try:
                observer(trace)
            except Exception:
                # the aggregation plane must never break trace retention
                log.exception("trace observer failed")

    def _export_log_line(self, trace: Trace) -> None:
        try:
            line = json.dumps({"event": "trace", **trace.to_dict()},
                              ensure_ascii=False,
                              separators=(",", ":"))
        except (TypeError, ValueError):
            # a non-serializable attr must never break the request path
            log.exception("trace %s not JSON-serializable",
                          trace.request_id)
            return
        if self._log_path:
            try:
                with self._log_lock:
                    with open(self._log_path, "a", encoding="utf-8") as f:
                        f.write(line + "\n")
            except OSError:
                log.exception("cannot append to %s", self._log_path)
        else:
            log.info("%s", line)

    # -- retrieval -----------------------------------------------------------
    def recent_traces(self) -> list:
        """Finished traces, newest first."""
        with self._lock:
            return list(self._recent)[::-1]

    def slowest_traces(self) -> list:
        """Finished traces, slowest first (bounded ring)."""
        with self._lock:
            entries = sorted(self._slow, reverse=True)
        return [t for _d, _s, t in entries]

    def find(self, request_id: str) -> Optional[Trace]:
        for t in self.recent_traces():
            if t.request_id == request_id:
                return t
        return None

    def clear(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slow.clear()

    # -- exports -------------------------------------------------------------
    @staticmethod
    def chrome_trace(traces) -> dict:
        """Chrome trace-event JSON (load in chrome://tracing or
        https://ui.perfetto.dev): one virtual thread per request."""
        events = []
        for tid, trace in enumerate(traces, start=1):
            events.extend(trace.chrome_events(tid))
        return {"traceEvents": events, "displayTimeUnit": "ms"}


_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    """Process-wide tracer (what :class:`ServingRuntime` and the CLI use
    by default, so the HTTP debug plane and every frontend agree on one
    ring buffer)."""
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer
