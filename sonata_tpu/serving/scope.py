"""sonata-scope: the aggregate observability plane.

PR-2 gave the serving stack counters and PR-4 gave it per-request span
trees, but nothing *aggregated*: "what is TTFB p99 over the last five
minutes", "what fraction of device time is padding waste", "are we
burning our latency budget" were unanswerable without scraping raw
traces.  This module turns the trace firehose into operable fleet
signals — four coupled pieces:

1. **Per-stage streaming quantiles** — every finished trace feeds
   fixed-memory :mod:`.sketches` per stage (phonemize, queue-wait,
   dispatch, decode-window, TTFB, e2e) over rolling 1m/5m/1h windows,
   exported as ``sonata_stage_quantile{stage,q,window}`` gauge
   callbacks and ``GET /debug/quantiles``.
2. **SLO burn-rate engine** — a declarative SLO table (``SONATA_SLO``,
   grammar ``stage:pNN:threshold`` / ``error_rate:fraction``) with
   SRE-style multi-window burn rates (fast 5m / slow 1h):
   ``sonata_slo_burn_rate{slo,window}`` and
   ``sonata_slo_budget_remaining{slo}``.  With
   ``SONATA_DEGRADE_ON_BURN=1``, sustained fast-window burn counts as
   pressure on the PR-6 degradation ladder, so the ladder reacts to
   user-visible latency, not just sheds.
3. **Dispatch-efficiency accounting** — every device dispatch reports
   its padded bucket shape and real row count (the PR-4 attribution
   channel); the scope accumulates
   ``sonata_dispatch_padding_waste_seconds_total{voice}`` and
   per-(batch,text,frame)-bucket hit/waste tables at
   ``GET /debug/buckets`` — the baseline artifact the ROADMAP's
   continuous-batching and bucket-audit items start from.
4. **Flight recorder** — a bounded ring of once-per-second process
   snapshots (queue depths, in-flight, healthy replicas, degradation
   level, dispatch/compile counters, burn rates) at
   ``GET /debug/timeline`` (JSON or ``?format=chrome``), auto-dumped to
   ``SONATA_TIMELINE_DUMP_DIR`` when the degradation ladder reaches
   level >= 2 or the hung-dispatch watchdog convicts a dispatch — every
   incident ships with its preceding minutes.

Cost model (the PR-4 bar): per-request work is one trace walk at finish
time (off the TTFB path) plus dict updates per *dispatch*; idle cost is
the 1 Hz recorder tick.  With ``SONATA_SCOPE=0`` nothing is installed
and every hook is a single module-global read.  The per-request stage
feed rides the tracer, so ``SONATA_TRACE=0`` also empties the
quantile/SLO streams (dispatch accounting, fed by the scheduler, keeps
flowing).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import degradation
from .sketches import QuantileSketch, RollingCounter, RollingSketch

log = logging.getLogger("sonata.serving")

SCOPE_ENV = "SONATA_SCOPE"
SLO_ENV = "SONATA_SLO"
DUMP_DIR_ENV = "SONATA_TIMELINE_DUMP_DIR"
TIMELINE_CAP_ENV = "SONATA_TIMELINE_CAP"
DEGRADE_ON_BURN_ENV = "SONATA_DEGRADE_ON_BURN"
BURN_PRESSURE_ENV = "SONATA_DEGRADE_BURN_RATE"

#: stages the quantile plane tracks; per-request stages (everything but
#: ``dispatch``) are fed from finished traces, ``dispatch`` from the
#: scheduler itself so one coalesced dispatch counts once, not once per
#: co-batched request
STAGES = ("phonemize", "queue-wait", "dispatch", "decode-window", "ttfb",
          "e2e")

#: (label, seconds, ring slots) — slot duration = window / slots
WINDOWS = (("1m", 60.0, 12), ("5m", 300.0, 15), ("1h", 3600.0, 30))

QUANTILES = (("p50", 0.5), ("p90", 0.9), ("p99", 0.99))

#: burn-rate windows (SRE multi-window convention: page on fast, hold on
#: slow); both must exist in WINDOWS-equivalent rolling counters
FAST_WINDOW = ("5m", 300.0, 15)
SLOW_WINDOW = ("1h", 3600.0, 30)

#: SLO table when SONATA_SLO is unset
DEFAULT_SLO = "ttfb:p95:2s,e2e:p99:10s,error_rate:0.01"

DEFAULT_TIMELINE_CAP = 600   # 10 minutes at 1 Hz
DEFAULT_TICK_INTERVAL_S = 1.0
DEFAULT_BURN_PRESSURE_RATE = 14.4  # SRE fast-burn page threshold
DUMP_MIN_INTERVAL_S = 30.0

#: the one definition of "this env knob is off" (mirrors tracing's)
_FALSY = ("0", "false", "off", "no")

_DURATION_RE = re.compile(r"^([0-9.]+)(ms|s|m)?$")


def _env_truthy(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    return raw.strip().lower() not in _FALSY


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def parse_duration_s(raw: str) -> float:
    """``2s`` / ``500ms`` / ``1.5`` (bare seconds) / ``2m`` → seconds."""
    m = _DURATION_RE.match(raw.strip().lower())
    if m is None:
        raise ValueError(f"unparseable duration {raw!r}")
    value = float(m.group(1))
    unit = m.group(2) or "s"
    return value * {"ms": 1e-3, "s": 1.0, "m": 60.0}[unit]


class SloSpec:
    """One declarative objective.

    Latency form (``stage:pNN:threshold``): at most ``1 - NN/100`` of
    the stage's observations may exceed ``threshold``.  Error form
    (``error_rate:fraction``): at most ``fraction`` of requests may
    finish with an error status.  ``budget`` is the allowed bad
    fraction; burn rate = observed bad fraction / budget, so 1.0 means
    "burning exactly the whole budget" and 14.4 is the classic
    fast-page threshold.
    """

    __slots__ = ("name", "kind", "stage", "quantile", "threshold_s",
                 "budget")

    def __init__(self, name: str, kind: str, *, stage: Optional[str] = None,
                 quantile: Optional[float] = None,
                 threshold_s: Optional[float] = None,
                 budget: float = 0.01):
        if budget <= 0 or budget >= 1:
            raise ValueError(f"SLO {name!r}: budget must be in (0, 1)")
        self.name = name
        self.kind = kind  # "latency" | "error_rate"
        self.stage = stage
        self.quantile = quantile
        self.threshold_s = threshold_s
        self.budget = budget

    def to_dict(self) -> dict:
        d = {"name": self.name, "kind": self.kind,
             "budget": round(self.budget, 6)}
        if self.kind == "latency":
            d.update(stage=self.stage, quantile=self.quantile,
                     threshold_s=self.threshold_s)
        return d


def parse_slos(raw: Optional[str] = None) -> List[SloSpec]:
    """Parse the ``SONATA_SLO`` grammar (falling back to the default
    table).  Raises ``ValueError`` on a malformed entry — a typo'd SLO
    must fail loudly at boot, not silently never alert."""
    raw = (raw if raw is not None
           else os.environ.get(SLO_ENV, "")).strip() or DEFAULT_SLO
    specs: List[SloSpec] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if parts[0] == "error_rate":
            if len(parts) != 2:
                raise ValueError(
                    f"SLO entry {entry!r}: expected error_rate:<fraction>")
            specs.append(SloSpec("error_rate", "error_rate",
                                 budget=float(parts[1])))
            continue
        if len(parts) != 3:
            raise ValueError(
                f"SLO entry {entry!r}: expected stage:pNN:threshold")
        stage, q_raw, threshold_raw = parts
        if stage not in STAGES:
            raise ValueError(
                f"SLO entry {entry!r}: unknown stage {stage!r} "
                f"(one of {', '.join(STAGES)})")
        if not q_raw.startswith("p"):
            raise ValueError(f"SLO entry {entry!r}: quantile must be pNN")
        pct = float(q_raw[1:])
        if not 0 < pct < 100:
            raise ValueError(f"SLO entry {entry!r}: pNN out of (0, 100)")
        specs.append(SloSpec(
            f"{stage}_{q_raw}", "latency", stage=stage, quantile=pct / 100.0,
            threshold_s=parse_duration_s(threshold_raw),
            budget=1.0 - pct / 100.0))
    if not specs:
        raise ValueError(f"SLO table {raw!r} parsed to nothing")
    names = [s.name for s in specs]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        # duplicates would silently share one counter set and
        # double-count every observation into the burn rate
        raise ValueError(f"SLO table {raw!r}: duplicate objective(s) "
                         f"{', '.join(dupes)}")
    return specs


#: metric families the scope exports, registered table-driven in
#: :meth:`Scope.bind_metrics` (the sonata-lint metricsdoc pass resolves
#: loop-registered literal tables like this one)
GAUGE_FAMILIES = (
    ("sonata_stage_quantile",
     "Rolling per-stage latency quantile in seconds, by stage, quantile "
     "(p50/p90/p99) and window (1m/5m/1h)."),
    ("sonata_slo_burn_rate",
     "SLO burn rate by objective and window (1.0 = consuming exactly "
     "the error budget; page on sustained fast-window burn)."),
    ("sonata_slo_budget_remaining",
     "Fraction of the slow-window error budget left per objective "
     "(negative = overspent)."),
)


class Scope:
    """Owns the sketches, SLO counters, bucket tables, and the flight
    recorder.  One per :class:`~sonata_tpu.serving.ServingRuntime`;
    installed process-globally (like the degradation ladder) so the
    scheduler and tracer feed it without holding a runtime reference.
    """

    def __init__(self, *, slos=None,
                 timeline_cap: Optional[int] = None,
                 dump_dir: Optional[str] = None,
                 tick_interval_s: float = DEFAULT_TICK_INTERVAL_S,
                 clock=None):
        self._clock = clock if clock is not None else time.monotonic
        self.slos = (parse_slos(slos) if slos is None or isinstance(slos, str)
                     else list(slos))
        self.tick_interval_s = max(0.05, tick_interval_s)
        self.timeline_cap = (timeline_cap if timeline_cap is not None
                             else _env_int(TIMELINE_CAP_ENV,
                                           DEFAULT_TIMELINE_CAP))
        self.dump_dir = (dump_dir if dump_dir is not None
                         else os.environ.get(DUMP_DIR_ENV) or None)
        self._degrade_on_burn = _env_truthy(DEGRADE_ON_BURN_ENV, False)
        self._burn_pressure_rate = _env_float(BURN_PRESSURE_ENV,
                                              DEFAULT_BURN_PRESSURE_RATE)

        #: stage -> window label -> RollingSketch
        self._stages: Dict[str, Dict[str, RollingSketch]] = {
            stage: {label: RollingSketch(seconds, slots, clock=self._clock)
                    for label, seconds, slots in WINDOWS}
            for stage in STAGES}
        #: merged-sketch memo per (stage, window): one merge serves a
        #: whole scrape's worth of quantile callbacks
        self._merged_cache: Dict[tuple, tuple] = {}
        self._merged_lock = threading.Lock()

        #: slo name -> window label -> RollingCounter
        self._slo_counts: Dict[str, Dict[str, RollingCounter]] = {
            spec.name: {label: RollingCounter(seconds, slots,
                                              clock=self._clock)
                        for label, seconds, slots in (FAST_WINDOW,
                                                      SLOW_WINDOW)}
            for spec in self.slos}
        self._latency_slos: Dict[str, List[SloSpec]] = {}
        for spec in self.slos:
            if spec.kind == "latency":
                self._latency_slos.setdefault(spec.stage, []).append(spec)
        self._error_slos = [s for s in self.slos if s.kind == "error_rate"]

        #: sonata-tenancy burn accounting: tenant -> slo name -> window
        #: label -> RollingCounter, created lazily on the tenant's first
        #: observation (the same SONATA_SLO objectives, counted per
        #: tenant so one tenant's burn cannot hide inside the global
        #: ring).  Empty — zero cost beyond one dict read — on
        #: tenancy-off processes.
        self._tenant_lock = threading.Lock()
        self._tenant_slo: Dict[str, Dict[str, Dict[str,
                                                   RollingCounter]]] = {}
        #: tenant -> padding-waste accumulators (chargeback rows on
        #: /debug/buckets): each dispatch's waste is pro-rated over the
        #: tenants running synthesis at that moment (the fair gate's
        #: active mix), attached by the runtime via attach_tenant_mix
        self._tenant_waste: Dict[str, dict] = {}
        self._tenant_mix_fn: Optional[Callable[[], dict]] = None

        # dispatch-efficiency accounting
        self._bucket_lock = threading.Lock()
        #: (batch, text, frame) bucket -> accumulators
        self._buckets: Dict[tuple, dict] = {}
        self._voice_waste: Dict[str, float] = {}
        self.dispatches_total = 0
        self.padding_waste_seconds_total = 0.0
        self.cold_compiles_total = 0
        #: cold-compile containment (ISSUE 9): once the boot warmup
        #: marks itself complete, any further ``compile=cold`` dispatch
        #: is a lattice-coverage regression — counted per voice
        #: (``sonata_runtime_cold_compiles_total``) and shipped as a
        #: flight-recorder incident, so it cannot land silently.
        #: ``_warmed_voices`` scopes the promise: None arms every voice
        #: (tests / single-voice processes); a set arms exactly the
        #: voices the boot warmup covered, so a voice legitimately
        #: loaded AFTER readiness does not false-alarm on its first
        #: compiles.
        self._warmup_complete = False
        self._warmed_voices: Optional[frozenset] = None
        self._runtime_cold: Dict[str, int] = {}

        # flight recorder
        self._timeline: "deque[dict]" = deque(maxlen=max(self.timeline_cap,
                                                         1))
        self._timeline_lock = threading.Lock()
        self._probes: Dict[str, Callable[[], Optional[float]]] = {}
        self._probes_lock = threading.Lock()
        self._last_level = 0
        #: per-reason rate-limit stamps: a repeated watchdog conviction
        #: must not re-dump every second, but it must also never starve
        #: a different incident class (a ladder escalation) of its dump
        self._last_dump_at: Dict[str, float] = {}
        self.dumps: List[str] = []  # paths written (newest last)
        self._breached: tuple = ()  # slo names burning > budget (fast)
        #: synthesis-cache stats source (ISSUE 15): the runtime attaches
        #: its SynthCache's ``cache_view`` so the debug plane and the
        #: flight recorder carry hit-ratio rows; None on cache-off
        #: processes (the snapshot then simply omits the section)
        self._cache_view_fn: Optional[Callable[[], dict]] = None
        self._started = time.monotonic()
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Scope":
        """Start the 1 Hz recorder thread (idempotent)."""
        if self._ticker is None or not self._ticker.is_alive():
            self._stop.clear()
            self._ticker = threading.Thread(target=self._tick_loop,
                                            name="sonata_scope_tick",
                                            daemon=True)
            self._ticker.start()
        return self

    def close(self) -> None:
        self._stop.set()
        ticker, self._ticker = self._ticker, None
        if ticker is not None:
            ticker.join(timeout=2.0)

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.tick()
            except Exception:
                # the recorder must never take the process down
                log.exception("scope tick failed")

    # -- per-stage quantile feed ---------------------------------------------
    def observe(self, stage: str, seconds: float) -> None:
        """One stage observation; also feeds that stage's latency SLOs."""
        windows = self._stages.get(stage)
        if windows is None or seconds < 0:
            return
        for sketch in windows.values():
            sketch.add(seconds)
        for spec in self._latency_slos.get(stage, ()):
            bad = seconds > spec.threshold_s
            for counter in self._slo_counts[spec.name].values():
                counter.record(bad=bad)

    # -- per-tenant SLO burn (sonata-tenancy) ---------------------------------
    def _tenant_rings(self, tenant: str, slo: str) -> Dict[str,
                                                           "RollingCounter"]:
        with self._tenant_lock:
            rings = self._tenant_slo.get(tenant, {}).get(slo)
        if rings is not None:
            return rings
        # construct outside the lock (first observation per (tenant,
        # slo) only); the double-checked setdefault keeps one winner
        fresh = {label: RollingCounter(seconds, slots,
                                       clock=self._clock)
                 for label, seconds, slots in (FAST_WINDOW,
                                               SLOW_WINDOW)}
        with self._tenant_lock:
            by_slo = self._tenant_slo.setdefault(tenant, {})
            return by_slo.setdefault(slo, fresh)

    def observe_tenant(self, tenant: Optional[str], stage: str,
                       seconds: float) -> None:
        """One tenant-attributed stage observation, feeding the
        tenant's own copy of that stage's latency SLO rings.  The
        GLOBAL rings are fed by :meth:`note_trace`/:meth:`observe` as
        before — this is strictly additive, a no-op when ``tenant`` is
        None (tenancy off)."""
        if tenant is None or seconds < 0:
            return
        for spec in self._latency_slos.get(stage, ()):
            bad = seconds > spec.threshold_s
            for counter in self._tenant_rings(tenant, spec.name).values():
                counter.record(bad=bad)

    def note_tenant_error(self, tenant: Optional[str], ok: bool) -> None:
        """One tenant-attributed request outcome for the error-rate
        SLOs (no-op when ``tenant`` is None)."""
        if tenant is None:
            return
        for spec in self._error_slos:
            for counter in self._tenant_rings(tenant, spec.name).values():
                counter.record(bad=not ok)

    def attach_tenant_mix(self, mix_fn: Callable[[], dict]) -> None:
        """Attach the tenancy plane's active-stream mix (tenant →
        running synthesis streams) so dispatch padding waste can be
        pro-rated into per-tenant chargeback rows."""
        self._tenant_mix_fn = mix_fn

    def tenant_burn_snapshot(self) -> dict:
        """{tenant: {slo: {window: burn_rate}}} — the per-tenant rows
        ``/debug/quantiles`` and the fleet merge serve."""
        budgets = {spec.name: spec.budget for spec in self.slos}
        with self._tenant_lock:
            out = {}
            for tenant, by_slo in sorted(self._tenant_slo.items()):
                rows = {}
                for slo, rings in by_slo.items():
                    budget = budgets.get(slo)
                    if not budget:
                        continue
                    rows[slo] = {
                        label: _round6(
                            None if (frac := ring.bad_fraction()) is None
                            else frac / budget)
                        for label, ring in rings.items()}
                out[tenant] = rows
            return out

    def note_trace(self, trace) -> None:
        """Feed one finished trace: per-request stages, TTFB, e2e, and
        the error-rate SLOs.  Runs at trace-finish time (after the last
        audio left), never on the TTFB path."""
        try:
            for span in trace.spans_snapshot():
                if span.end is None or span.parent_id is None:
                    continue
                if span.name in ("phonemize", "queue-wait", "decode-window"):
                    self.observe(span.name, span.end - span.start)
                elif span.name == "stream-emit":
                    ttfb_ms = span.attrs.get("ttfb_ms")
                    if ttfb_ms is not None:
                        self.observe("ttfb", float(ttfb_ms) / 1e3)
            self.observe("e2e", trace.duration_s)
            ok = trace.status == "ok"
            for spec in self._error_slos:
                for counter in self._slo_counts[spec.name].values():
                    counter.record(bad=not ok)
        except Exception:
            log.exception("scope trace feed failed")

    # -- dispatch-efficiency accounting --------------------------------------
    def note_dispatch(self, duration_s: float, attrs: dict) -> None:
        """One device dispatch, with the attribution the model annotated
        (:func:`~sonata_tpu.serving.tracing.annotate_dispatch_group`).

        ``waste = duration * padding_ratio`` uses the dispatch span's
        own headline ``padding_ratio`` (padding rows / padded batch), so
        this accounting and the per-dispatch trace attribution can never
        disagree — the pinned test in tests/test_scope.py holds them
        equal.
        """
        self.observe("dispatch", duration_s)
        ratio = attrs.get("padding_ratio")
        voice = attrs.get("voice")
        cold = attrs.get("compile") == "cold"
        key = (attrs.get("batch_bucket"), attrs.get("text_bucket"),
               attrs.get("frame_bucket"))
        waste = duration_s * float(ratio) if ratio is not None else 0.0
        runtime_cold = False
        with self._bucket_lock:
            self.dispatches_total += 1
            if cold:
                self.cold_compiles_total += 1
                # `scaled` = a non-default length scale changed the
                # frame estimate: that shape was never in the lattice's
                # coverage promise, so its compile is expected work,
                # not a regression
                if (self._warmup_complete
                        and not attrs.get("scaled")
                        and (self._warmed_voices is None
                             or voice in self._warmed_voices)):
                    runtime_cold = True
                    v = voice if voice is not None else ""
                    self._runtime_cold[v] = self._runtime_cold.get(v, 0) + 1
        if runtime_cold:
            # a compile AFTER warmup completion means the lattice missed
            # a shape real traffic hits: loud log + incident dump (the
            # preceding minutes show which traffic found the hole)
            log.error(
                "runtime cold compile after warmup completion "
                "(voice=%s bucket=%s): the warmup lattice does not "
                "cover this shape", voice, key)
            self.note_incident("cold-compile")
        if ratio is None:
            return  # a model that never annotated (no bucket story)
        # per-tenant chargeback (sonata-tenancy): a dispatch batch can
        # mix tenants' sentences, so its waste is pro-rated over the
        # tenants with running synthesis streams at this moment
        mix_fn = self._tenant_mix_fn
        mix = None
        if mix_fn is not None:
            try:
                mix = mix_fn() or None
            except Exception:
                mix = None
        if mix is not None:
            total_streams = sum(mix.values()) or 1
            with self._tenant_lock:
                for tenant, streams in mix.items():
                    acc = self._tenant_waste.get(tenant)
                    if acc is None:
                        acc = self._tenant_waste[tenant] = {
                            "dispatches": 0, "seconds": 0.0,
                            "waste_seconds": 0.0}
                    frac = streams / total_streams
                    acc["dispatches"] += 1
                    acc["seconds"] += duration_s * frac
                    acc["waste_seconds"] += waste * frac
        with self._bucket_lock:
            self.padding_waste_seconds_total += waste
            if voice is not None:
                self._voice_waste[voice] = (
                    self._voice_waste.get(voice, 0.0) + waste)
            acc = self._buckets.get(key)
            if acc is None:
                acc = self._buckets[key] = {
                    "dispatches": 0, "rows": 0, "padding_rows": 0,
                    "seconds": 0.0, "waste_seconds": 0.0,
                    "cold_compiles": 0}
            acc["dispatches"] += 1
            acc["rows"] += int(attrs.get("rows", 0))
            acc["padding_rows"] += int(attrs.get("padding_rows", 0))
            acc["seconds"] += duration_s
            acc["waste_seconds"] += waste
            if cold:
                acc["cold_compiles"] += 1

    def padding_waste_seconds(self, voice: str) -> float:
        with self._bucket_lock:
            return self._voice_waste.get(voice, 0.0)

    # -- cold-compile containment ---------------------------------------------
    def mark_warmup_complete(self, voices=None) -> None:
        """The boot warmup finished: from here on, a ``compile=cold``
        dispatch counts as a runtime cold compile (a lattice-coverage
        hole) and lands a flight-recorder incident.  ``voices`` scopes
        the promise to the voice ids the lattice actually covered —
        a voice loaded via LoadVoice *after* readiness made no coverage
        promise, and its legitimate first compiles must not alarm.
        None (the default) arms every voice."""
        with self._bucket_lock:
            self._warmup_complete = True
            self._warmed_voices = (None if voices is None
                                   else frozenset(voices))

    @property
    def warmup_complete(self) -> bool:
        with self._bucket_lock:
            return self._warmup_complete

    def runtime_cold_compiles(self, voice: str) -> float:
        """Cold compiles after warmup completion, per voice (the
        ``sonata_runtime_cold_compiles_total`` callback)."""
        with self._bucket_lock:
            return float(self._runtime_cold.get(voice, 0))

    def runtime_cold_compiles_total(self) -> int:
        with self._bucket_lock:
            return sum(self._runtime_cold.values())

    # -- quantile / SLO queries ----------------------------------------------
    def _merged(self, stage: str, window: str) -> QuantileSketch:
        """Merged sketch for (stage, window), memoized so one scrape's 9
        quantile callbacks per pair pay a single merge.  Invalidated by
        the rolling sketch's add-generation (new data) and its slot
        epoch (time passing expires old slots even with no adds)."""
        rolling = self._stages[stage][window]
        stamp = (rolling.generation,
                 int(self._clock() / rolling.slot_s))
        key = (stage, window)
        with self._merged_lock:
            cached = self._merged_cache.get(key)
            if cached is not None and cached[0] == stamp:
                return cached[1]
        merged = rolling.merged()
        with self._merged_lock:
            self._merged_cache[key] = (stamp, merged)
        return merged

    def quantile(self, stage: str, q: float,
                 window: str) -> Optional[float]:
        if stage not in self._stages:
            return None
        return self._merged(stage, window).quantile(q)

    def burn_rate(self, slo: str, window: str) -> Optional[float]:
        """Observed bad fraction / budget for one window, or None while
        the window is empty."""
        counters = self._slo_counts.get(slo)
        spec = next((s for s in self.slos if s.name == slo), None)
        if counters is None or spec is None or window not in counters:
            return None
        frac = counters[window].bad_fraction()
        if frac is None:
            return None
        return frac / spec.budget

    def budget_remaining(self, slo: str) -> Optional[float]:
        """1 - slow-window burn: the fraction of the error budget left
        at the current slow-window spend (negative = overspent)."""
        burn = self.burn_rate(slo, SLOW_WINDOW[0])
        if burn is None:
            return None
        return 1.0 - burn

    @property
    def breached_slos(self) -> tuple:
        """SLOs whose fast-window burn exceeded 1.0 at the last tick."""
        return self._breached

    @property
    def slo_breach(self) -> bool:
        return bool(self._breached)

    # -- flight recorder ------------------------------------------------------
    def add_probe(self, name: str,
                  fn: Callable[[], Optional[float]]) -> None:
        """Register a named scalar source sampled into every snapshot."""
        with self._probes_lock:
            self._probes[name] = fn

    def remove_probe(self, name: str) -> None:
        with self._probes_lock:
            self._probes.pop(name, None)

    def tick(self) -> dict:
        """Record one snapshot (the recorder thread calls this at 1 Hz;
        tests call it directly).  Also the burn→degradation coupling and
        the level-triggered auto-dump live here, so they cost nothing on
        any request path."""
        snap: dict = {"ts": round(time.time(), 3),
                      "up_s": round(time.monotonic() - self._started, 1)}
        with self._probes_lock:
            probes = list(self._probes.items())
        for name, fn in probes:
            try:
                value = fn()
            except Exception:
                continue
            if value is not None:
                snap[name] = round(float(value), 3)
        with self._bucket_lock:
            snap["dispatches_total"] = self.dispatches_total
            snap["padding_waste_seconds_total"] = round(
                self.padding_waste_seconds_total, 3)
            snap["cold_compiles_total"] = self.cold_compiles_total
            snap["runtime_cold_compiles_total"] = sum(
                self._runtime_cold.values())
        breached = []
        for spec in self.slos:
            burn = self.burn_rate(spec.name, FAST_WINDOW[0])
            if burn is None:
                continue
            snap[f"burn:{spec.name}"] = round(burn, 3)
            if burn > 1.0:
                breached.append(spec.name)
        self._breached = tuple(breached)
        snap["slo_breach"] = 1 if breached else 0
        ladder = degradation.installed()
        level = ladder.current_level() if ladder is not None else 0
        snap["degradation_level"] = level
        with self._timeline_lock:
            self._timeline.append(snap)
        # burn → ladder pressure (opt-in): sustained fast-window burn
        # above the page threshold is user-visible latency pain
        if (self._degrade_on_burn and breached
                and any(snap.get(f"burn:{name}", 0.0)
                        > self._burn_pressure_rate for name in breached)):
            degradation.note_burn()
        # level-triggered auto-dump: the ladder reaching reject-batch or
        # worse means an incident is in progress — persist the preceding
        # minutes while they are still in the ring
        if level >= 2 and self._last_level < 2:
            self.dump(f"degradation-level-{level}")
        self._last_level = level
        return snap

    def note_incident(self, reason: str) -> Optional[str]:
        """An out-of-band conviction (the watchdog): dump the timeline
        now, rate-limited."""
        return self.dump(reason)

    def dump(self, reason: str) -> Optional[str]:
        """Write the current timeline ring to ``dump_dir`` (no-op when
        unset), at most once per ``DUMP_MIN_INTERVAL_S`` per reason."""
        if not self.dump_dir:
            return None
        now = self._clock()
        with self._timeline_lock:
            last = self._last_dump_at.get(reason)
            if last is not None and now - last < DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump_at[reason] = now
            snapshots = list(self._timeline)
        path = os.path.join(
            self.dump_dir,
            f"timeline-{int(time.time())}-{reason}.json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump({"reason": reason, "wall_time": time.time(),
                           "interval_s": self.tick_interval_s,
                           "snapshots": snapshots}, f)
        except OSError:
            log.exception("flight-recorder dump to %s failed", path)
            return None
        self.dumps.append(path)
        log.warning("flight recorder dumped %d snapshot(s) to %s (%s)",
                    len(snapshots), path, reason)
        return path

    # -- synthesis-cache rows (serving/synthcache.py, ISSUE 15) ---------------
    def attach_cache_stats(self, view_fn: Callable[[], dict]) -> None:
        """Attach the synthesis cache's ``cache_view`` callable so the
        scope plane serves hit-ratio rows (``/debug/quantiles``
        ``synth_cache`` section) next to the quantile/SLO state."""
        self._cache_view_fn = view_fn

    def cache_snapshot(self) -> Optional[dict]:
        fn = self._cache_view_fn
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            # a closing cache must never break the debug plane
            return None

    # -- debug-plane views ----------------------------------------------------
    def quantiles_snapshot(self) -> dict:
        doc = {
            "windows": [label for label, _s, _n in WINDOWS],
            "stages": {
                stage: {label: self._merged(stage, label).to_dict()
                        for label, _s, _n in WINDOWS}
                for stage in STAGES}}
        cache = self.cache_snapshot()
        if cache is not None:
            doc["synth_cache"] = cache
        tenants = self.tenant_burn_snapshot()
        if tenants:
            # per-tenant SLO burn rows (sonata-tenancy); absent on
            # tenancy-off processes, so the pre-tenancy shape is intact
            doc["tenants"] = tenants
        return doc

    def slo_snapshot(self) -> dict:
        out = []
        for spec in self.slos:
            out.append({
                **spec.to_dict(),
                "burn_rate": {
                    label: _round6(self.burn_rate(spec.name, label))
                    for label in (FAST_WINDOW[0], SLOW_WINDOW[0])},
                "budget_remaining": _round6(
                    self.budget_remaining(spec.name))})
        return {"slos": out, "breached": list(self._breached)}

    def buckets_snapshot(self) -> dict:
        with self._bucket_lock:
            rows = [{"batch_bucket": b, "text_bucket": t, "frame_bucket": f,
                     **{k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in acc.items()}}
                    for (b, t, f), acc in sorted(
                        self._buckets.items(),
                        key=lambda kv: kv[1]["waste_seconds"],
                        reverse=True)]
            return {"dispatches_total": self.dispatches_total,
                    "padding_waste_seconds_total": round(
                        self.padding_waste_seconds_total, 6),
                    "cold_compiles_total": self.cold_compiles_total,
                    "runtime_cold_compiles_total": sum(
                        self._runtime_cold.values()),
                    "warmup_complete": self._warmup_complete,
                    "per_voice_waste_seconds": {
                        v: round(w, 6)
                        for v, w in sorted(self._voice_waste.items())},
                    "buckets": rows,
                    **self._tenant_waste_rows()}

    def _tenant_waste_rows(self) -> dict:
        """``{"tenant_waste": [...]}`` rows for the buckets view, or
        ``{}`` (tenancy off — the pre-tenancy document shape holds)."""
        with self._tenant_lock:
            if not self._tenant_waste:
                return {}
            rows = [{"tenant": tenant,
                     **{k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in acc.items()}}
                    for tenant, acc in sorted(
                        self._tenant_waste.items(),
                        key=lambda kv: kv[1]["waste_seconds"],
                        reverse=True)]
            return {"tenant_waste": rows}

    def timeline_snapshot(self) -> list:
        with self._timeline_lock:
            return list(self._timeline)

    # -- cross-process export (the fleet hop, ISSUE 13) -----------------------
    def export_snapshot(self) -> dict:
        """Compact mergeable export of the whole aggregation plane,
        served per node at ``GET /debug/scope/export`` and folded
        fleet-wide by the sonata-mesh router's
        :class:`~sonata_tpu.serving.fleetscope.FleetScope`.

        Ships sketch *bins and slot epochs*, never samples (the
        :mod:`.sketches` export contract), the SLO counter rings, the
        totals, and the top padding-waste buckets.  ``wall_time`` lets
        the importer measure this node's clock offset against its own
        fetch window (what re-bases stitched traces).  Cost: one pass
        over the rolling rings under their slot locks — no merging, no
        quantile math — so serving it at the fleet scrape cadence stays
        inside the PR-7 <=2% overhead bar (measured: FLEET_r01.json
        ``export_overhead_ratio``)."""
        from .sketches import EXPORT_VERSION

        with self._bucket_lock:
            totals = {
                "dispatches_total": self.dispatches_total,
                "padding_waste_seconds_total": round(
                    self.padding_waste_seconds_total, 6),
                "cold_compiles_total": self.cold_compiles_total,
                "runtime_cold_compiles_total": sum(
                    self._runtime_cold.values())}
            top_rows = [
                {"batch_bucket": b, "text_bucket": t, "frame_bucket": f,
                 **{k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in acc.items()}}
                for (b, t, f), acc in sorted(
                    self._buckets.items(),
                    key=lambda kv: kv[1]["waste_seconds"],
                    reverse=True)[:8]]
        doc = {
            "v": EXPORT_VERSION,
            "wall_time": time.time(),
            "windows": [label for label, _s, _n in WINDOWS],
            "stages": {
                stage: {label: self._stages[stage][label].export()
                        for label, _s, _n in WINDOWS}
                for stage in STAGES},
            "slos": {
                spec.name: {
                    label: self._slo_counts[spec.name][label].export()
                    for label in (FAST_WINDOW[0], SLOW_WINDOW[0])}
                for spec in self.slos},
            "slo_table": [spec.to_dict() for spec in self.slos],
            "totals": totals,
            "top_waste_buckets": top_rows}
        # the synthesis cache's view (hit counters, byte usage, and the
        # hot_keys LRU head the fleet-cache replication pass consumes)
        # rides the same export; absent on cache-off nodes — importers
        # ignore unknown/missing keys, so no EXPORT_VERSION bump
        cache = self.cache_snapshot()
        if cache is not None:
            doc["synth_cache"] = cache
        # per-tenant SLO rings + waste rows (sonata-tenancy) ride the
        # same export, keyed additively like synth_cache: absent on
        # tenancy-off nodes, importers use .get — no EXPORT_VERSION bump
        with self._tenant_lock:
            if self._tenant_slo:
                doc["tenant_slos"] = {
                    tenant: {
                        slo: {label: ring.export()
                              for label, ring in rings.items()}
                        for slo, rings in by_slo.items()}
                    for tenant, by_slo in self._tenant_slo.items()}
        tenant_waste = self._tenant_waste_rows()
        if tenant_waste:
            doc.update(tenant_waste)
        return doc

    def timeline_chrome(self) -> dict:
        """Counter-track export: load next to ``/debug/traces``' chrome
        file and the recorder's gauges line up under the spans."""
        events = []
        for snap in self.timeline_snapshot():
            ts_us = snap["ts"] * 1e6
            for key, value in snap.items():
                if key == "ts" or not isinstance(value, (int, float)):
                    continue
                events.append({"ph": "C", "pid": 1, "tid": 0,
                               "name": key, "ts": round(ts_us, 1),
                               "args": {"value": value}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    # -- metrics export -------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Attach the scope's gauge-callback families to a registry.

        Process-lifetime series (like ``sonata_up``): nothing per-voice
        is created here, so there is no teardown to record.  The family
        table is loop-registered — the sonata-lint metricsdoc pass
        resolves the literal names through the loop variable."""
        families = {}
        for name, help in GAUGE_FAMILIES:
            families[name] = registry.gauge(name, help)
        quant = families["sonata_stage_quantile"]
        for stage in STAGES:
            for wlabel, _s, _n in WINDOWS:
                for qlabel, q in QUANTILES:
                    quant.labels(
                        stage=stage, q=qlabel, window=wlabel
                    ).set_function(
                        lambda s=stage, qq=q, w=wlabel:
                        self.quantile(s, qq, w))
        burn = families["sonata_slo_burn_rate"]
        remaining = families["sonata_slo_budget_remaining"]
        for spec in self.slos:
            for wlabel in (FAST_WINDOW[0], SLOW_WINDOW[0]):
                burn.labels(slo=spec.name, window=wlabel).set_function(
                    lambda n=spec.name, w=wlabel: self.burn_rate(n, w))
            remaining.labels(slo=spec.name).set_function(
                lambda n=spec.name: self.budget_remaining(n))


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


def scope_enabled() -> bool:
    """``SONATA_SCOPE`` (default on) — the runtime's construction gate."""
    return _env_truthy(SCOPE_ENV, True)


# ---------------------------------------------------------------------------
# process-global install: the scheduler and tracer feed the active scope
# without a runtime reference (mirrors degradation's install pattern)
# ---------------------------------------------------------------------------

_installed: Optional[Scope] = None


def install(scope: Scope) -> None:
    global _installed
    _installed = scope
    from . import tracing

    tracing.set_trace_observer(_on_trace_finished)


def uninstall(scope: Scope) -> None:
    """Remove ``scope`` if it is the installed one (a newer runtime's
    scope is never clobbered by an older runtime's close)."""
    global _installed
    if _installed is scope:
        _installed = None
        from . import tracing

        tracing.set_trace_observer(None)


def installed() -> Optional[Scope]:
    return _installed


def _on_trace_finished(trace) -> None:
    scope = _installed
    if scope is not None:
        scope.note_trace(trace)


def note_dispatch(duration_s: float, attrs: dict) -> None:
    """Scheduler hook: one device dispatch finished (no-op — a single
    module-global read — when no scope is installed)."""
    scope = _installed
    if scope is not None:
        scope.note_dispatch(duration_s, attrs)


def note_watchdog() -> None:
    """Scheduler hook: the watchdog convicted a dispatch — ship the
    flight recorder's preceding minutes with the incident."""
    scope = _installed
    if scope is not None:
        scope.note_incident("watchdog")
