"""Streaming quantile sketches with rolling time windows.

The serving plane's histograms (:mod:`~sonata_tpu.utils.profiling`) are
cumulative-forever: they answer "what was TTFB p99 *since boot*", which
goes stale the moment traffic changes.  The aggregation layer
(:mod:`.scope`) needs "p99 over the last five minutes" — a windowed
quantile — without keeping raw samples.  This module provides the two
primitives:

- :class:`QuantileSketch` — a DDSketch-style log-bucketed sketch
  (Masson et al., VLDB '19): values map to geometric buckets
  ``gamma**i``, so any reported quantile is within a configurable
  *relative* error (default 1%) of the true value, memory is bounded
  (lowest buckets collapse past ``max_bins``), and two sketches
  **merge** by adding bucket counts — the property that makes rolling
  windows cheap.
- :class:`RollingSketch` — a ring of per-slot sketches covering one
  time window (e.g. 12 × 5 s slots = 1 minute).  ``add`` writes the
  current slot; ``merged`` combines the live slots, so expiry is
  O(slots) bookkeeping, never a rescan of observations.
- :class:`RollingCounter` — the same ring for plain good/bad counts
  (what the SLO burn-rate math consumes).

Everything takes an injectable ``clock`` so the window-expiry tests run
on a fake clock instead of sleeping.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

#: smallest value (seconds) the sketch distinguishes from zero; serving
#: latencies below a microsecond are all "instant" for SLO purposes
MIN_TRACKED = 1e-6

DEFAULT_RELATIVE_ACCURACY = 0.01
DEFAULT_MAX_BINS = 512


class QuantileSketch:
    """Fixed-memory mergeable quantile sketch (relative-error bound).

    Not thread-safe by itself: callers (:class:`RollingSketch`, tests)
    hold their own lock.  ``quantile(q)`` returns a value within
    ``relative_accuracy`` of the true q-quantile of everything added.
    """

    __slots__ = ("relative_accuracy", "_gamma", "_log_gamma", "_max_bins",
                 "_bins", "_zero_count", "count", "sum", "min", "max")

    def __init__(self, relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 max_bins: int = DEFAULT_MAX_BINS):
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError("relative_accuracy must be in (0, 1)")
        self.relative_accuracy = relative_accuracy
        self._gamma = (1.0 + relative_accuracy) / (1.0 - relative_accuracy)
        self._log_gamma = math.log(self._gamma)
        self._max_bins = max(8, int(max_bins))
        self._bins: Dict[int, int] = {}
        self._zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- recording -----------------------------------------------------------
    def _key(self, value: float) -> int:
        return math.ceil(math.log(value) / self._log_gamma)

    def add(self, value: float, count: int = 1) -> None:
        if count <= 0:
            return
        value = float(value)
        self.count += count
        self.sum += value * count
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if value < MIN_TRACKED:
            self._zero_count += count
            return
        key = self._key(value)
        self._bins[key] = self._bins.get(key, 0) + count
        if len(self._bins) > self._max_bins:
            self._collapse()

    def _collapse(self) -> None:
        """Fold the lowest buckets together until within ``max_bins``.

        Collapsing the *low* end sacrifices resolution where SLO math
        never looks (the fast tail), keeping the p9x buckets exact."""
        keys = sorted(self._bins)
        while len(keys) > self._max_bins:
            lowest = keys.pop(0)
            self._bins[keys[0]] = (self._bins.get(keys[0], 0)
                                   + self._bins.pop(lowest))

    def merge(self, other: "QuantileSketch") -> None:
        """Fold ``other`` into self (bucket-wise addition)."""
        if other.count == 0:
            return
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self._zero_count += other._zero_count
        for key, c in other._bins.items():
            self._bins[key] = self._bins.get(key, 0) + c
        if len(self._bins) > self._max_bins:
            self._collapse()

    # -- queries -------------------------------------------------------------
    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (0 <= q <= 1), or None while empty."""
        if self.count == 0:
            return None
        q = min(max(q, 0.0), 1.0)
        rank = q * (self.count - 1)
        if rank < self._zero_count:
            return 0.0
        running = self._zero_count
        for key in sorted(self._bins):
            running += self._bins[key]
            if running > rank:
                # geometric bucket midpoint: within relative_accuracy of
                # anything that mapped into bucket ``key``
                return (2.0 * self._gamma ** key) / (self._gamma + 1.0)
        return self.max if self.max > -math.inf else None

    def count_above(self, threshold: float) -> int:
        """How many observations exceeded ``threshold`` (bucket-granular:
        accurate to the sketch's relative error)."""
        if threshold < MIN_TRACKED:
            return self.count - self._zero_count
        cut = self._key(threshold)
        return sum(c for key, c in self._bins.items() if key > cut)

    def to_dict(self) -> dict:
        return {"count": self.count,
                "sum": round(self.sum, 6),
                "min": None if self.count == 0 else round(self.min, 6),
                "max": None if self.count == 0 else round(self.max, 6),
                "p50": _round(self.quantile(0.5)),
                "p90": _round(self.quantile(0.9)),
                "p99": _round(self.quantile(0.99))}


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


class _SlotRing:
    """Shared slot bookkeeping for the rolling containers.

    The ring holds ``slots + 1`` entries: the write slot plus a full
    window of read slots, so a query never includes observations older
    than ``window_s`` by more than one slot duration."""

    def __init__(self, window_s: float, slots: int, clock=None):
        if window_s <= 0 or slots <= 0:
            raise ValueError("window_s and slots must be positive")
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.slot_s = self.window_s / self.slots
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        #: slot index -> (epoch, payload); epoch = int(now / slot_s)
        self._ring: Dict[int, tuple] = {}

    def _epoch(self) -> int:
        return int(self._clock() / self.slot_s)

    def _current(self, factory):
        """The (epoch, payload) pair for the write slot, creating or
        recycling it as the clock advances.  Caller holds the lock."""
        epoch = self._epoch()
        idx = epoch % (self.slots + 1)
        entry = self._ring.get(idx)
        if entry is None or entry[0] != epoch:
            entry = (epoch, factory())
            self._ring[idx] = entry
        return entry

    def _live(self):
        """Payloads of every non-expired slot.  Caller holds the lock."""
        now_epoch = self._epoch()
        return [payload for epoch, payload in self._ring.values()
                if now_epoch - epoch <= self.slots]


class RollingSketch(_SlotRing):
    """A :class:`QuantileSketch` over a rolling time window."""

    def __init__(self, window_s: float, slots: int = 12, *,
                 relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 clock=None):
        super().__init__(window_s, slots, clock=clock)
        self._accuracy = relative_accuracy
        #: bumped on every add — lets consumers (the scope's per-scrape
        #: merge memo) invalidate on new data instead of guessing a TTL
        self.generation = 0

    def _factory(self) -> QuantileSketch:
        return QuantileSketch(self._accuracy)

    def add(self, value: float) -> None:
        with self._lock:
            self.generation += 1
            self._current(self._factory)[1].add(value)

    def merged(self) -> QuantileSketch:
        """One sketch combining every live slot (cheap: bucket adds).

        The whole merge runs under the ring lock: a live slot's bin dict
        is still being written by concurrent ``add`` calls, and merging
        it unlocked races dict iteration against insertion."""
        out = QuantileSketch(self._accuracy)
        with self._lock:
            for sketch in self._live():
                out.merge(sketch)
        return out


class RollingCounter(_SlotRing):
    """Good/bad event counts over a rolling time window (SLO feed)."""

    def __init__(self, window_s: float, slots: int = 12, *, clock=None):
        super().__init__(window_s, slots, clock=clock)

    @staticmethod
    def _factory() -> list:
        return [0, 0]  # [good, bad]

    def record(self, *, bad: bool, count: int = 1) -> None:
        with self._lock:
            self._current(self._factory)[1][1 if bad else 0] += count

    def totals(self) -> tuple:
        """(good, bad) over the live window (summed under the lock so
        the pair can't tear against a concurrent ``record``)."""
        with self._lock:
            live = self._live()
            good = sum(slot[0] for slot in live)
            bad = sum(slot[1] for slot in live)
        return good, bad

    def bad_fraction(self) -> Optional[float]:
        """bad / (good + bad), or None with no observations."""
        good, bad = self.totals()
        total = good + bad
        if total == 0:
            return None
        return bad / total
