"""Streaming quantile sketches with rolling time windows.

The serving plane's histograms (:mod:`~sonata_tpu.utils.profiling`) are
cumulative-forever: they answer "what was TTFB p99 *since boot*", which
goes stale the moment traffic changes.  The aggregation layer
(:mod:`.scope`) needs "p99 over the last five minutes" — a windowed
quantile — without keeping raw samples.  This module provides the two
primitives:

- :class:`QuantileSketch` — a DDSketch-style log-bucketed sketch
  (Masson et al., VLDB '19): values map to geometric buckets
  ``gamma**i``, so any reported quantile is within a configurable
  *relative* error (default 1%) of the true value, memory is bounded
  (lowest buckets collapse past ``max_bins``), and two sketches
  **merge** by adding bucket counts — the property that makes rolling
  windows cheap.
- :class:`RollingSketch` — a ring of per-slot sketches covering one
  time window (e.g. 12 × 5 s slots = 1 minute).  ``add`` writes the
  current slot; ``merged`` combines the live slots, so expiry is
  O(slots) bookkeeping, never a rescan of observations.
- :class:`RollingCounter` — the same ring for plain good/bad counts
  (what the SLO burn-rate math consumes).

Everything takes an injectable ``clock`` so the window-expiry tests run
on a fake clock instead of sleeping.

**Cross-process export** (ISSUE 13): every container serializes to a
compact versioned payload — bucket *bins* and slot *epochs*, never raw
samples — via ``export()``, and imports fold back with
:func:`merged_from_export` / :func:`totals_from_export`.  Because merge
is bucket-wise addition, a fleet sketch merged from N nodes' exports is
*identical* to the sketch of the pooled observations, so fleet
quantiles inherit the same relative-error guarantee (the pinned
cross-process bound in tests/test_fleetscope.py).  Slot epochs are
re-based to the importer's clock through the exporter's own
``now_epoch`` (monotonic clocks are not comparable across hosts, ages
are), and a version or accuracy mismatch raises the typed
:class:`SketchImportError` — folding incompatible bins silently would
corrupt every fleet quantile downstream.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Tuple

#: smallest value (seconds) the sketch distinguishes from zero; serving
#: latencies below a microsecond are all "instant" for SLO purposes
MIN_TRACKED = 1e-6

DEFAULT_RELATIVE_ACCURACY = 0.01
DEFAULT_MAX_BINS = 512

#: version stamp on every export payload; importers reject anything else
#: (typed, loud) instead of folding bins whose meaning may have changed
EXPORT_VERSION = 1


class SketchImportError(ValueError):
    """An export payload this build cannot import: unknown version,
    incompatible relative accuracy (bucket keys are only comparable
    between sketches sharing one gamma), or a malformed document.
    Typed so cross-process importers (the sonata-mesh fleet scraper)
    fail loudly per node instead of quietly merging garbage into
    fleet-wide quantiles."""


def _check_version(data, what: str) -> None:
    if not isinstance(data, dict):
        raise SketchImportError(
            f"{what} export must be a dict, got {type(data).__name__}")
    v = data.get("v")
    if v != EXPORT_VERSION:
        raise SketchImportError(
            f"{what} export version {v!r} is not importable by this "
            f"build (speaks version {EXPORT_VERSION})")


class QuantileSketch:
    """Fixed-memory mergeable quantile sketch (relative-error bound).

    Not thread-safe by itself: callers (:class:`RollingSketch`, tests)
    hold their own lock.  ``quantile(q)`` returns a value within
    ``relative_accuracy`` of the true q-quantile of everything added.
    """

    __slots__ = ("relative_accuracy", "_gamma", "_log_gamma", "_max_bins",
                 "_bins", "_zero_count", "count", "sum", "min", "max")

    def __init__(self, relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 max_bins: int = DEFAULT_MAX_BINS):
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError("relative_accuracy must be in (0, 1)")
        self.relative_accuracy = relative_accuracy
        self._gamma = (1.0 + relative_accuracy) / (1.0 - relative_accuracy)
        self._log_gamma = math.log(self._gamma)
        self._max_bins = max(8, int(max_bins))
        self._bins: Dict[int, int] = {}
        self._zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- recording -----------------------------------------------------------
    def _key(self, value: float) -> int:
        return math.ceil(math.log(value) / self._log_gamma)

    def add(self, value: float, count: int = 1) -> None:
        if count <= 0:
            return
        value = float(value)
        self.count += count
        self.sum += value * count
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if value < MIN_TRACKED:
            self._zero_count += count
            return
        key = self._key(value)
        self._bins[key] = self._bins.get(key, 0) + count
        if len(self._bins) > self._max_bins:
            self._collapse()

    def _collapse(self) -> None:
        """Fold the lowest buckets together until within ``max_bins``.

        Collapsing the *low* end sacrifices resolution where SLO math
        never looks (the fast tail), keeping the p9x buckets exact."""
        keys = sorted(self._bins)
        while len(keys) > self._max_bins:
            lowest = keys.pop(0)
            self._bins[keys[0]] = (self._bins.get(keys[0], 0)
                                   + self._bins.pop(lowest))

    def merge(self, other: "QuantileSketch") -> None:
        """Fold ``other`` into self (bucket-wise addition)."""
        if other.count == 0:
            return
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self._zero_count += other._zero_count
        for key, c in other._bins.items():
            self._bins[key] = self._bins.get(key, 0) + c
        if len(self._bins) > self._max_bins:
            self._collapse()

    # -- queries -------------------------------------------------------------
    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (0 <= q <= 1), or None while empty."""
        if self.count == 0:
            return None
        q = min(max(q, 0.0), 1.0)
        rank = q * (self.count - 1)
        if rank < self._zero_count:
            return 0.0
        running = self._zero_count
        for key in sorted(self._bins):
            running += self._bins[key]
            if running > rank:
                # geometric bucket midpoint: within relative_accuracy of
                # anything that mapped into bucket ``key``
                return (2.0 * self._gamma ** key) / (self._gamma + 1.0)
        return self.max if self.max > -math.inf else None

    def count_above(self, threshold: float) -> int:
        """How many observations exceeded ``threshold`` (bucket-granular:
        accurate to the sketch's relative error)."""
        if threshold < MIN_TRACKED:
            return self.count - self._zero_count
        cut = self._key(threshold)
        return sum(c for key, c in self._bins.items() if key > cut)

    def to_dict(self) -> dict:
        return {"count": self.count,
                "sum": round(self.sum, 6),
                "min": None if self.count == 0 else round(self.min, 6),
                "max": None if self.count == 0 else round(self.max, 6),
                "p50": _round(self.quantile(0.5)),
                "p90": _round(self.quantile(0.9)),
                "p99": _round(self.quantile(0.99))}

    # -- cross-process export --------------------------------------------------
    def export(self) -> dict:
        """Versioned, JSON-safe payload: bins + counts, never samples.
        Bin keys serialize as strings (JSON object keys)."""
        return export_quantile_sketch(self)

    @classmethod
    def from_export(cls, data) -> "QuantileSketch":
        """Rebuild from :meth:`export` output; raises the typed
        :class:`SketchImportError` on version mismatch or malformed
        payloads."""
        _check_version(data, "QuantileSketch")
        try:
            sk = cls(float(data["ra"]))
            for k, c in dict(data["bins"]).items():
                sk._bins[int(k)] = int(c)
            sk._zero_count = int(data["zero"])
            sk.count = int(data["count"])
            sk.sum = float(data["sum"])
            if sk.count > 0:
                sk.min = float(data["min"])
                sk.max = float(data["max"])
        except (KeyError, TypeError, ValueError) as e:
            raise SketchImportError(
                f"malformed QuantileSketch export: {e}") from None
        if len(sk._bins) > sk._max_bins:
            sk._collapse()
        return sk

    def merge_export(self, data) -> None:
        """Fold an exported sketch into self.  Accuracy must match:
        bucket key ``i`` means ``gamma**i`` and gammas differing means
        the same key names a different value — silently adding such bins
        would shift every downstream quantile."""
        other = QuantileSketch.from_export(data)
        if abs(other.relative_accuracy - self.relative_accuracy) > 1e-12:
            raise SketchImportError(
                f"cannot merge sketch with relative_accuracy="
                f"{other.relative_accuracy} into one with "
                f"{self.relative_accuracy}: bucket keys are incompatible")
        self.merge(other)


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


def export_quantile_sketch(sk: "QuantileSketch") -> dict:
    """Serialize one sketch (the :meth:`QuantileSketch.export` body).

    A module function — not a method call — so the ring containers can
    serialize their slot sketches while holding their slot lock without
    the serializer sharing a bare name with the lock-taking ring
    ``export`` methods (the repo-wide lock-order pass resolves calls by
    bare name, like the mesh ``view()``/``snapshot()`` note)."""
    return {"v": EXPORT_VERSION,
            "ra": sk.relative_accuracy,
            "bins": {str(k): c for k, c in sk._bins.items()},
            "zero": sk._zero_count,
            "count": sk.count,
            "sum": sk.sum,
            "min": None if sk.count == 0 else sk.min,
            "max": None if sk.count == 0 else sk.max}


class _SlotRing:
    """Shared slot bookkeeping for the rolling containers.

    The ring holds ``slots + 1`` entries: the write slot plus a full
    window of read slots, so a query never includes observations older
    than ``window_s`` by more than one slot duration."""

    def __init__(self, window_s: float, slots: int, clock=None):
        if window_s <= 0 or slots <= 0:
            raise ValueError("window_s and slots must be positive")
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.slot_s = self.window_s / self.slots
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        #: slot index -> (epoch, payload); epoch = int(now / slot_s)
        self._ring: Dict[int, tuple] = {}

    def _epoch(self) -> int:
        return int(self._clock() / self.slot_s)

    def _current(self, factory):
        """The (epoch, payload) pair for the write slot, creating or
        recycling it as the clock advances.  Caller holds the lock."""
        epoch = self._epoch()
        idx = epoch % (self.slots + 1)
        entry = self._ring.get(idx)
        if entry is None or entry[0] != epoch:
            entry = (epoch, factory())
            self._ring[idx] = entry
        return entry

    def _live(self):
        """Payloads of every non-expired slot.  Caller holds the lock."""
        now_epoch = self._epoch()
        return [payload for epoch, payload in self._ring.values()
                if now_epoch - epoch <= self.slots]


class RollingSketch(_SlotRing):
    """A :class:`QuantileSketch` over a rolling time window."""

    def __init__(self, window_s: float, slots: int = 12, *,
                 relative_accuracy: float = DEFAULT_RELATIVE_ACCURACY,
                 clock=None):
        super().__init__(window_s, slots, clock=clock)
        self._accuracy = relative_accuracy
        #: bumped on every add — lets consumers (the scope's per-scrape
        #: merge memo) invalidate on new data instead of guessing a TTL
        self.generation = 0

    def _factory(self) -> QuantileSketch:
        return QuantileSketch(self._accuracy)

    def add(self, value: float) -> None:
        with self._lock:
            self.generation += 1
            self._current(self._factory)[1].add(value)

    def merged(self) -> QuantileSketch:
        """One sketch combining every live slot (cheap: bucket adds).

        The whole merge runs under the ring lock: a live slot's bin dict
        is still being written by concurrent ``add`` calls, and merging
        it unlocked races dict iteration against insertion."""
        out = QuantileSketch(self._accuracy)
        with self._lock:
            for sketch in self._live():
                out.merge(sketch)
        return out

    def export(self) -> dict:
        """Versioned ring payload: per-slot bins + slot epochs, plus the
        exporter's ``now_epoch`` so the importer can turn epochs into
        *ages* (monotonic epochs are process-local; ages cross hosts).
        Runs wholly under the ring lock for the same reason as
        :meth:`merged`."""
        with self._lock:
            now_epoch = self._epoch()
            ring = [{"epoch": epoch,
                     "sketch": export_quantile_sketch(payload)}
                    for epoch, payload in self._ring.values()
                    if now_epoch - epoch <= self.slots]
        return {"v": EXPORT_VERSION, "kind": "sketch",
                "window_s": self.window_s, "slots": self.slots,
                "ra": self._accuracy, "now_epoch": now_epoch,
                "ring": ring}


class RollingCounter(_SlotRing):
    """Good/bad event counts over a rolling time window (SLO feed)."""

    def __init__(self, window_s: float, slots: int = 12, *, clock=None):
        super().__init__(window_s, slots, clock=clock)

    @staticmethod
    def _factory() -> list:
        return [0, 0]  # [good, bad]

    def record(self, *, bad: bool, count: int = 1) -> None:
        with self._lock:
            self._current(self._factory)[1][1 if bad else 0] += count

    def totals(self) -> tuple:
        """(good, bad) over the live window (summed under the lock so
        the pair can't tear against a concurrent ``record``)."""
        with self._lock:
            live = self._live()
            good = sum(slot[0] for slot in live)
            bad = sum(slot[1] for slot in live)
        return good, bad

    def bad_fraction(self) -> Optional[float]:
        """bad / (good + bad), or None with no observations."""
        good, bad = self.totals()
        total = good + bad
        if total == 0:
            return None
        return bad / total

    def export(self) -> dict:
        """Versioned ring payload (good/bad per slot + slot epochs) —
        the counter twin of :meth:`RollingSketch.export`."""
        with self._lock:
            now_epoch = self._epoch()
            ring = [{"epoch": epoch, "good": payload[0], "bad": payload[1]}
                    for epoch, payload in self._ring.values()
                    if now_epoch - epoch <= self.slots]
        return {"v": EXPORT_VERSION, "kind": "counter",
                "window_s": self.window_s, "slots": self.slots,
                "now_epoch": now_epoch, "ring": ring}


# ---------------------------------------------------------------------------
# ring-export importers (the router side of the fleet hop)
# ---------------------------------------------------------------------------

def _ring_meta(data, what: str) -> tuple:
    _check_version(data, what)
    try:
        window_s = float(data["window_s"])
        slots = int(data["slots"])
        now_epoch = int(data["now_epoch"])
        ring = list(data["ring"])
    except (KeyError, TypeError, ValueError) as e:
        raise SketchImportError(f"malformed {what} export: {e}") from None
    if window_s <= 0 or slots <= 0:
        raise SketchImportError(
            f"malformed {what} export: window_s={window_s} slots={slots}")
    return window_s, slots, now_epoch, ring


def ring_from_export(data) -> Tuple[float, float, List[tuple]]:
    """Parse a :meth:`RollingSketch.export` payload into
    ``(window_s, slot_s, [(age_s, QuantileSketch), ...])`` where
    ``age_s`` is the slot's age *at export time*.  The caller adds its
    own scrape age before expiring slots against the window.  Raises
    :class:`SketchImportError` (typed, loud) on any malformed entry —
    validation happens at import, not lazily at query time."""
    window_s, slots, now_epoch, ring = _ring_meta(data, "RollingSketch")
    slot_s = window_s / slots
    out: List[tuple] = []
    for entry in ring:
        try:
            age_s = (now_epoch - int(entry["epoch"])) * slot_s
            sketch = QuantileSketch.from_export(entry["sketch"])
        except (KeyError, TypeError, ValueError) as e:
            raise SketchImportError(
                f"malformed RollingSketch slot: {e}") from None
        if age_s <= window_s:  # anything older exports as expired: no-op
            out.append((age_s, sketch))
    return window_s, slot_s, out


def merged_from_export(data, *, extra_age_s: float = 0.0,
                       relative_accuracy: Optional[float] = None
                       ) -> QuantileSketch:
    """One sketch folding a :meth:`RollingSketch.export` payload,
    expiring slots whose export-time age plus ``extra_age_s`` (the
    importer's scrape staleness) exceeds the window.  An empty or
    fully-expired export merges as a no-op (count 0)."""
    window_s, slot_s, ring = ring_from_export(data)
    ra = (relative_accuracy if relative_accuracy is not None
          else float(data.get("ra", DEFAULT_RELATIVE_ACCURACY)))
    out = QuantileSketch(ra)
    for age_s, sketch in ring:
        if age_s + extra_age_s > window_s:
            continue
        if abs(sketch.relative_accuracy - ra) > 1e-12:
            raise SketchImportError(
                f"slot relative_accuracy {sketch.relative_accuracy} != "
                f"ring accuracy {ra}")
        out.merge(sketch)
    return out


def counter_ring_from_export(data) -> Tuple[float, float, List[tuple]]:
    """Parse a :meth:`RollingCounter.export` payload into
    ``(window_s, slot_s, [(age_s, good, bad), ...])`` — the counter
    twin of :func:`ring_from_export`, validated whole at import."""
    window_s, slots, now_epoch, ring = _ring_meta(data, "RollingCounter")
    slot_s = window_s / slots
    out: List[tuple] = []
    for entry in ring:
        try:
            age_s = (now_epoch - int(entry["epoch"])) * slot_s
            g, b = int(entry["good"]), int(entry["bad"])
        except (KeyError, TypeError, ValueError) as e:
            raise SketchImportError(
                f"malformed RollingCounter slot: {e}") from None
        if age_s <= window_s:
            out.append((age_s, g, b))
    return window_s, slot_s, out


def totals_from_export(data, *, extra_age_s: float = 0.0) -> tuple:
    """(good, bad) folding a :meth:`RollingCounter.export` payload with
    the same age-expiry contract as :func:`merged_from_export`."""
    window_s, _slot_s, ring = counter_ring_from_export(data)
    good = bad = 0
    for age_s, g, b in ring:
        if age_s + extra_age_s > window_s:
            continue
        good += g
        bad += b
    return good, bad
