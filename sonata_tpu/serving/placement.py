"""sonata-placement: self-healing voice placement for the mesh.

PR 12's router treats voice state as fire-and-forget: ``LoadVoice`` fans
out to whichever nodes are reachable *at call time*, so a SIGKILLed-and-
restarted backend rejoins membership holding **no voices** and answers
``NOT_FOUND`` for every id the fleet is supposed to serve — the gap
DEPLOY.md documented ("voices belong in node boot config") and the
ROADMAP carried as the fleet-tier leftover.  At millions-of-users scale
voice state must be a *reconciled desired-state control plane*, not a
best-effort broadcast.  This module is that plane, in four pieces:

- **Desired-state registry.**  Every voice op through the router —
  ``LoadVoice`` (config path), ``UnloadVoice``, ``SetSynthesisOptions``
  (the encoded request, replayable verbatim) — is recorded with a
  monotonically increasing revision.  An unload leaves a *tombstone*:
  a stale node rejoining with the voice still resident is retired, and
  no code path can resurrect an unloaded voice (pinned).  Voices the
  registry has never seen (node *boot-config* voices, pre-placement
  fleets) are deliberately left alone — wire compatibility.
- **Placement map.**  Each desired voice is assigned to
  ``SONATA_PLACEMENT_REPLICAS`` nodes (default 0 = every node, the
  PR-12 fan-out shape), spread by least RAM pressure (estimated
  ``SONATA_PLACEMENT_VOICE_MB`` per placed voice).  Assignment is
  sticky: a healthy placement never moves, and a holder that trips its
  breaker or leaves membership is replaced within one reconcile
  interval — while a voice is *under*-replicated its dead holders stay
  assigned, so a rejoining node gets its voices replayed instead of
  orphan-retired.
- **Anti-entropy reconciler.**  Rides the router's existing per-node
  prober threads (:meth:`PlacementPlane.on_probe_cycle`, the
  fleetscope pattern — a wedged node can only ever stall its own
  reconcile).  Each cycle diffs the node's *actual* loaded-voice set —
  scraped from the ``voices=`` line on ``/readyz``, falling back to the
  ``sonata_voice_loaded{voice}`` gauge — against desired state, and
  replays the difference: missed loads (plus recorded synthesis
  options) to rejoining/restarted nodes, unloads for tombstoned or
  no-longer-placed voices.  The ``mesh.reconcile`` failpoint fires
  inside every cycle; an injected error counts toward *that node's*
  breaker on its own consecutive reconcile-failure counter (separate
  from the probe and route counters, so the 4x-more-frequent probe
  successes cannot launder it) and an injected hang stalls only that
  node's prober thread.
- **RAM-budgeted LRU eviction.**  ``SONATA_PLACEMENT_RAM_BUDGET_MB``
  (0 = off) bounds each node's estimated resident set; over budget, the
  least-recently-routed placed voice is evicted from that node — but
  **never** a voice with in-flight or resident iteration-loop streams
  routed through this router (the per-(node, voice) outstanding count
  guards both the eviction pick and the unload op).  An evicted voice
  is re-placed onto a node with budget room by the next reconcile.

Routing becomes **voice-aware**: :meth:`MeshRouter.pick(voice=...)` is
restricted to converged holders (nodes whose scraped actual set carries
the voice; nodes with an unknown actual set — no metrics plane — stay
permissive).  When the registry knows a voice but no holder has
converged yet, the pick raises the typed :class:`VoiceWarming` refusal;
``route_stream`` absorbs it with a bounded router-side wait
(``SONATA_PLACEMENT_WAIT_MS``) so a request racing a placement replay
waits for convergence instead of failing.

Lock order: the router lock is taken *outside* the plane lock
(``pick`` → ``routable_for``/``touch``), so the plane never calls a
router-locking method while holding its own lock — reconcile gathers
its router-side view first, computes under the plane lock, and applies
ops with no lock held.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core import OperationError
from . import faults
from .replicas import OPEN, _env_float, _env_int

log = logging.getLogger("sonata.serving")

PLACEMENT_REPLICAS_ENV = "SONATA_PLACEMENT_REPLICAS"
PLACEMENT_RECONCILE_INTERVAL_ENV = "SONATA_PLACEMENT_RECONCILE_INTERVAL_S"
PLACEMENT_RAM_BUDGET_ENV = "SONATA_PLACEMENT_RAM_BUDGET_MB"
PLACEMENT_VOICE_MB_ENV = "SONATA_PLACEMENT_VOICE_MB"
PLACEMENT_WAIT_ENV = "SONATA_PLACEMENT_WAIT_MS"

#: 0 = place every voice on every node (the PR-12 fan-out shape, wire
#: compatible: nothing changes until an operator opts into subsets)
DEFAULT_REPLICAS = 0
DEFAULT_RECONCILE_INTERVAL_S = 2.0
#: 0 = no RAM budget, no eviction
DEFAULT_RAM_BUDGET_MB = 0.0
#: per-voice resident-RAM estimate driving spread and the budget
DEFAULT_VOICE_MB = 512.0
#: bounded router-side wait for a warming voice before the typed refusal
DEFAULT_WAIT_MS = 1000.0

#: ops the reconciler replays, the label values of
#: ``sonata_placement_reconcile_ops_total{op=...}``
PLACEMENT_OPS = ("load", "unload", "set_options")
#: label values of ``sonata_placement_evictions_total{reason=...}``:
#: ``ram-budget`` (LRU under the node budget) and ``unplaced`` (the
#: rebalancer dropped a holder — trip replacement or target trim)
PLACEMENT_EVICTION_REASONS = ("ram-budget", "unplaced")

#: fleet-level placement gauge families, loop-registered like the
#: scope's GAUGE_FAMILIES so the sonata-lint metricsdoc pass resolves
#: the names
PLACEMENT_GAUGE_FAMILIES = (
    ("sonata_placement_desired",
     "Nodes assigned to hold this voice by the placement map "
     "(SONATA_PLACEMENT_REPLICAS, default every node), per voice."),
    ("sonata_placement_converged",
     "Assigned nodes whose scraped actual loaded-voice set carries "
     "this voice, per voice; converged == desired is the healthy "
     "steady state."),
)


class VoiceWarming(OperationError):
    """Typed refusal: the registry knows this voice but no routable
    node has converged on holding it yet (a placement replay is in
    flight).  Maps to gRPC UNAVAILABLE with a ``voice-warming`` detail
    — clients retry, exactly like a ``draining`` refusal."""


class ProbeCadence:
    """Per-node cadence gate for work that rides the mesh prober
    threads at a slower interval than the health probe itself.

    The prober calls its plane hooks every ``probe_interval_s``; a
    plane that wants its own (slower) cadence per node gates each call
    through :meth:`due`.  Factored out of this module's reconciler so
    the anti-entropy passes that ride the probers — voice-placement
    reconcile (here) and hot-set cache replication
    (``serving/fleetcache.py``) — share one gating implementation.
    Thread-safe: each prober thread gates its own node, but membership
    churn can interleave indexes."""

    __slots__ = ("interval_s", "_clock", "_lock", "_attempt_at")

    def __init__(self, interval_s: float, clock=None):
        self.interval_s = float(interval_s)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        #: node index -> monotonic stamp of the last gated attempt
        self._attempt_at: Dict[int, float] = {}

    def due(self, index: int) -> bool:
        """True (and stamp the attempt) when ``index``'s cadence has
        elapsed — the first call for a node is always due."""
        now = self._clock()
        with self._lock:
            last = self._attempt_at.get(index)
            if last is None or now - last >= self.interval_s:
                self._attempt_at[index] = now
                return True
            return False


class _DesiredVoice:
    """One voice's desired state: config path to replay loads from,
    the last recorded synthesis-options payload, and revisions."""

    __slots__ = ("voice_id", "config_path", "revision",
                 "options_payload", "options_revision",
                 "restore_tombstone")

    def __init__(self, voice_id: str, config_path: str, revision: int):
        self.voice_id = voice_id
        self.config_path = config_path
        self.revision = revision
        self.options_payload: Optional[bytes] = None
        self.options_revision = 0
        #: tombstone revision this load cleared (if any), so a rolled-
        #: back load (forget_load) can RESTORE it — a LoadVoice that
        #: reached zero nodes must not silently erase an unload
        self.restore_tombstone: Optional[int] = None


class PlacementPlane:
    """Desired-state voice registry + placement map + reconciler over a
    :class:`~sonata_tpu.serving.mesh.MeshRouter` membership.

    Transport-agnostic like the router itself: the three ``apply_*``
    callables (``apply_load(node, config_path)``,
    ``apply_unload(node, voice_id)``,
    ``apply_options(node, payload)``) are supplied by the frontend
    (real gRPC unaries in ``mesh_server``, plain fakes in the tests),
    so every line of registry/placement/reconcile logic is shared.
    """

    def __init__(self, router, *,
                 replicas: Optional[int] = None,
                 reconcile_interval_s: Optional[float] = None,
                 ram_budget_mb: Optional[float] = None,
                 voice_mb: Optional[float] = None,
                 wait_ms: Optional[float] = None,
                 apply_load: Optional[Callable] = None,
                 apply_unload: Optional[Callable] = None,
                 apply_options: Optional[Callable] = None,
                 clock=None):
        self.router = router
        self._clock = clock if clock is not None else time.monotonic
        self.replicas = max(0, (
            replicas if replicas is not None
            else _env_int(PLACEMENT_REPLICAS_ENV, DEFAULT_REPLICAS)))
        self.reconcile_interval_s = max(0.05, (
            reconcile_interval_s if reconcile_interval_s is not None
            else _env_float(PLACEMENT_RECONCILE_INTERVAL_ENV,
                            DEFAULT_RECONCILE_INTERVAL_S)))
        self.ram_budget_mb = (
            ram_budget_mb if ram_budget_mb is not None
            else _env_float(PLACEMENT_RAM_BUDGET_ENV,
                            DEFAULT_RAM_BUDGET_MB))
        self.voice_mb = max(1e-6, (
            voice_mb if voice_mb is not None
            else _env_float(PLACEMENT_VOICE_MB_ENV, DEFAULT_VOICE_MB)))
        self.wait_budget_s = max(0.0, (
            wait_ms if wait_ms is not None
            else _env_float(PLACEMENT_WAIT_ENV, DEFAULT_WAIT_MS))) / 1e3
        self._apply_load = apply_load
        self._apply_unload = apply_unload
        self._apply_options = apply_options

        self._lock = threading.Lock()
        self._revision = 0
        self._desired: Dict[str, _DesiredVoice] = {}
        #: voice_id -> ordered node indexes (the placement map)
        self._assign: Dict[str, List[int]] = {}
        #: voice_id -> revision at which it was unloaded; a tombstoned
        #: voice found resident on a rejoining node is retired, never
        #: resurrected
        self._tombstones: Dict[str, int] = {}
        #: (node index, voice_id) -> options revision replayed there
        self._applied_opts: Dict[tuple, int] = {}
        #: voice_id -> monotonic stamp of the last pick (the LRU clock)
        self._last_used: Dict[str, float] = {}
        #: per-node reconcile cadence riding the prober threads
        self._cadence = ProbeCadence(self.reconcile_interval_s,
                                     clock=self._clock)
        self.stats = {"cycles": 0, "reconcile_failures": 0,
                      "op_failures": 0, "ops_load": 0, "ops_unload": 0,
                      "ops_set_options": 0, "evictions_ram_budget": 0,
                      "evictions_unplaced": 0}

        # metric bookkeeping (lazy per-voice series, exact teardown)
        self._registry = None
        self._families: dict = {}
        self._series_lock = threading.Lock()
        self._voice_series: Dict[str, list] = {}

    # -- desired-state registry ------------------------------------------------
    def record_load(self, voice_id: str, config_path: str) -> bool:
        """Record a LoadVoice as desired state (clearing any tombstone)
        and place the voice.  Returns whether this call *created* the
        entry — a failed synchronous load uses that to
        :meth:`forget_load` instead of leaving ghost desired state."""
        with self._lock:
            self._revision += 1
            dv = self._desired.get(voice_id)
            created = dv is None
            if created:
                dv = _DesiredVoice(voice_id, config_path, self._revision)
                self._desired[voice_id] = dv
                self._last_used.setdefault(voice_id, self._clock())
            else:
                dv.config_path = config_path
                dv.revision = self._revision
            cleared = self._tombstones.pop(voice_id, None)
            if created and cleared is not None:
                dv.restore_tombstone = cleared
            # a fresh explicit load always places — over-budget nodes
            # shed their LRU voice at the next reconcile (hot in, cold
            # out); only reconcile-time RE-placements respect the
            # budget filter, so an evicted cold voice cannot ping-pong
            # back onto a full node
            self._rebalance_locked(new_vid=voice_id)
        self._ensure_voice_series(voice_id)
        return created

    def forget_load(self, voice_id: str) -> None:
        """Roll back a :meth:`record_load` whose op reached no node at
        all (the RPC failed typed) — as if the load was never asked
        for, INCLUDING re-erecting the tombstone it cleared: a failed
        load must not resurrect a previously unloaded voice."""
        with self._lock:
            dv = self._desired.pop(voice_id, None)
            self._assign.pop(voice_id, None)
            self._last_used.pop(voice_id, None)
            if dv is not None and dv.restore_tombstone is not None:
                self._tombstones[voice_id] = dv.restore_tombstone
        self._drop_voice_series(voice_id)

    def forget_unload(self, voice_id: str) -> None:
        """Roll back a :meth:`record_unload` that failed typed having
        found the voice nowhere (a NOT_FOUND on an id neither the
        registry nor any node knows): the tombstone comes back out, so
        a node later boot-loading that id is not silently retired —
        boot-config voices the router never *successfully* operated on
        stay untouched."""
        with self._lock:
            self._tombstones.pop(voice_id, None)

    def record_unload(self, voice_id: str) -> bool:
        """Record an UnloadVoice: drop desired state and leave a
        tombstone, so any node still resident (or rejoining with the
        voice) is retired by reconcile.  Returns whether the voice was
        desired."""
        with self._lock:
            known = voice_id in self._desired
            self._revision += 1
            self._desired.pop(voice_id, None)
            self._assign.pop(voice_id, None)
            self._last_used.pop(voice_id, None)
            self._tombstones[voice_id] = self._revision
            for key in [k for k in self._applied_opts
                        if k[1] == voice_id]:
                self._applied_opts.pop(key, None)
        self._drop_voice_series(voice_id)
        return known

    def record_options(self, voice_id: str, payload: bytes) -> bool:
        """Record a SetSynthesisOptions payload (replayed verbatim to
        every holder, late joiners included).  Returns False when the
        voice is unknown to the registry (boot-config voices keep the
        PR-12 fan-out path)."""
        with self._lock:
            dv = self._desired.get(voice_id)
            if dv is None:
                return False
            self._revision += 1
            dv.options_payload = payload
            dv.options_revision = self._revision
        return True

    def has_voice(self, voice_id: str) -> bool:
        with self._lock:
            return voice_id in self._desired

    def desired_count(self, voice_id: str) -> int:
        with self._lock:
            return len(self._assign.get(voice_id, ()))

    def converged_count(self, voice_id: str) -> int:
        """Assigned nodes whose scraped actual set carries the voice."""
        with self._lock:
            idxs = set(self._assign.get(voice_id, ()))
        return sum(1 for n in self.router.nodes
                   if n.index in idxs and n.loaded_voices is not None
                   and voice_id in n.loaded_voices)

    def assigned_nodes(self, voice_id: str) -> list:
        with self._lock:
            idxs = set(self._assign.get(voice_id, ()))
        return [n for n in self.router.nodes if n.index in idxs]

    def note_applied(self, node, voice_id: str) -> None:
        """A synchronous (RPC-path) load reached ``node``: stamp the
        current options revision as applied there, so reconcile does
        not re-send options the fan-out just delivered."""
        with self._lock:
            dv = self._desired.get(voice_id)
            if dv is not None and dv.options_payload is not None:
                self._applied_opts[(node.index, voice_id)] = \
                    dv.options_revision

    # -- routing surface (called under the ROUTER lock) ------------------------
    def routable_for(self, voice_id: str) -> Optional[frozenset]:
        """Node indexes a request for ``voice_id`` may route to, or
        None when the registry does not know the voice (unrestricted —
        boot-config voices keep working).  A node with an *unknown*
        actual set (no metrics plane) stays permissive; a node known
        not to hold the voice is excluded."""
        with self._lock:
            if voice_id not in self._desired:
                return None
        return frozenset(
            n.index for n in self.router.nodes
            if n.loaded_voices is None or voice_id in n.loaded_voices)

    def touch(self, voice_id: str) -> None:
        """Stamp the LRU clock: this voice just took a request.
        Registry-unknown ids (boot-config voices, client typos) are
        ignored — they have no placement to keep warm, and recording
        every id a client ever sent would grow the table unboundedly."""
        with self._lock:
            if voice_id in self._desired:
                self._last_used[voice_id] = self._clock()

    # -- placement map ---------------------------------------------------------
    def _eligible(self, node) -> bool:
        # plain attribute reads — never the router lock (see the module
        # docstring's lock-order note)
        return (node.state != OPEN and node.ready and not node.draining
                and not node.scope_stale)

    def _pressure_locked(self, index: int) -> int:
        return sum(1 for a in self._assign.values() if index in a)

    def _fits_budget_locked(self, index: int) -> bool:
        if self.ram_budget_mb <= 0:
            return True
        return ((self._pressure_locked(index) + 1) * self.voice_mb
                <= self.ram_budget_mb)

    def _target_locked(self) -> int:
        n = len(self.router.nodes)
        return n if self.replicas <= 0 else min(self.replicas, n)

    def _rebalance_locked(self, new_vid: Optional[str] = None) -> None:
        """Recompute the placement map against current eligibility.

        Sticky by construction: a healthy placement never moves.  A
        voice below target gains the least-pressured eligible nodes —
        respecting the RAM-budget filter except for ``new_vid`` (a
        fresh explicit load lands regardless; eviction makes room).
        Once target is met by eligible holders, dead entries are
        dropped (counted ``unplaced``) — but while a voice is *under*
        target its ineligible holders stay assigned, so a
        transiently-tripped only-holder gets a replay on rejoin
        instead of an orphan retirement."""
        nodes = self.router.nodes
        by_index = {n.index: n for n in nodes}
        target = self._target_locked()
        for vid in sorted(self._desired,
                          key=lambda v: self._desired[v].revision):
            assign = [i for i in self._assign.get(vid, [])
                      if i in by_index]
            elig = [i for i in assign if self._eligible(by_index[i])]
            inelig = [i for i in assign if i not in elig]
            if len(elig) < target:
                candidates = [n for n in nodes
                              if self._eligible(n)
                              and n.index not in assign
                              and (vid == new_vid
                                   or self._fits_budget_locked(n.index))]
                candidates.sort(key=lambda n: (
                    self._pressure_locked(n.index), n.index))
                for n in candidates[: target - len(elig)]:
                    elig.append(n.index)
                    log.info("placement: voice %s placed on node %s",
                             vid, n.node_id)
            new_assign = elig[:target]
            if len(new_assign) < target:
                # under-replicated: keep dead holders — they may rejoin
                # still holding the voice, and replay beats retirement
                new_assign = new_assign + inelig
            dropped = [i for i in assign if i not in new_assign]
            if dropped:
                self.stats["evictions_unplaced"] += len(dropped)
                log.info(
                    "placement: voice %s no longer placed on node(s) %s",
                    vid, [by_index[i].node_id for i in dropped])
            self._assign[vid] = new_assign

    def _evict_for_budget_locked(self, node, outstanding: dict) -> None:
        """LRU-evict this node's placed voices down to the RAM budget.
        A voice with in-flight (or resident iteration-loop) streams
        routed through this router is never evicted."""
        if self.ram_budget_mb <= 0:
            return
        idx = node.index
        placed = [vid for vid, a in self._assign.items() if idx in a]
        while len(placed) * self.voice_mb > self.ram_budget_mb:
            victims = sorted(
                (vid for vid in placed
                 if outstanding.get(vid, 0) == 0),
                key=lambda v: self._last_used.get(v, 0.0))
            if not victims:
                # every placed voice has live streams: over budget but
                # nothing is safely evictable — retry next cycle
                log.warning(
                    "placement: node %s is over its %g MB budget but "
                    "every placed voice has in-flight streams; "
                    "deferring eviction", node.node_id,
                    self.ram_budget_mb)
                return
            vid = victims[0]
            self._assign[vid] = [i for i in self._assign[vid]
                                 if i != idx]
            placed.remove(vid)
            self.stats["evictions_ram_budget"] += 1
            log.info("placement: node %s evicted voice %s (LRU, RAM "
                     "budget %g MB)", node.node_id, vid,
                     self.ram_budget_mb)

    # -- reconcile (rides the mesh prober threads) -----------------------------
    def on_probe_cycle(self, node) -> None:
        """Called by the router's prober after every health cycle:
        run one reconcile cycle for ``node`` when the (slower)
        reconcile cadence is due."""
        if self._cadence.due(node.index):
            self.run_cycle(node)

    def run_cycle(self, node) -> bool:
        """One guarded reconcile cycle: a raise — the ``mesh.reconcile``
        failpoint, a failed replay op — is counted and charged to *that
        node's* breaker on the dedicated reconcile-failure counter; a
        clean cycle resets only that counter."""
        try:
            self.reconcile_node(node)
            self.router.note_reconcile_success(node)
            return True
        except Exception as e:
            with self._lock:
                self.stats["reconcile_failures"] += 1
            self.router.note_reconcile_failure(
                node, f"{type(e).__name__}: {e}")
            log.warning("placement: reconcile cycle for node %s "
                        "failed: %s", node.node_id, e)
            return False

    def reconcile_node(self, node) -> list:
        """Diff ``node``'s actual loaded-voice set against desired
        state and replay the difference.  Returns the ops applied
        (``(kind, voice_id)`` tuples).  Raises on an injected fault or
        a failed op — callers wanting breaker accounting go through
        :meth:`run_cycle`."""
        faults.fire("mesh.reconcile")
        actual, outstanding = self.router.voice_load_view(node)
        with self._lock:
            self.stats["cycles"] += 1
            if node.state == OPEN or node.draining:
                # unreachable or mid-deploy: nothing to replay — but a
                # node that went OPEN may be a restart in progress, so
                # forget what options we once applied there (replayed
                # on rejoin; the actual-set scrape re-drives loads)
                for key in [k for k in self._applied_opts
                            if k[0] == node.index]:
                    self._applied_opts.pop(key, None)
                return []
            self._rebalance_locked()
            self._evict_for_budget_locked(node, outstanding)
            ops = self._diff_locked(node, actual, outstanding)
        return self._apply(node, ops)

    def _diff_locked(self, node, actual, outstanding: dict) -> list:
        if actual is None:
            # actual set unknown (no metrics plane / pre-placement
            # backend): nothing can be diffed safely — PR-12 semantics
            return []
        ops = []
        for vid, dv in self._desired.items():
            if node.index not in self._assign.get(vid, ()):
                continue
            if vid not in actual:
                ops.append(("load", vid, dv.config_path,
                            dv.options_payload, dv.options_revision))
            elif (dv.options_payload is not None
                  and self._applied_opts.get((node.index, vid), 0)
                  < dv.options_revision):
                ops.append(("set_options", vid, dv.options_payload,
                            dv.options_revision))
        for vid in sorted(actual):
            retire = vid in self._tombstones
            orphan = (vid in self._desired
                      and node.index not in self._assign.get(vid, ()))
            if not (retire or orphan):
                continue  # unknown to the registry: boot-config voice
            if outstanding.get(vid, 0) > 0:
                continue  # never unload under live streams; next cycle
            ops.append(("unload", vid))
        return ops

    def _apply(self, node, ops: list) -> list:
        applied, failures = [], []
        for op in ops:
            kind, vid = op[0], op[1]
            try:
                if kind == "load":
                    if self._apply_load is None:
                        continue
                    _, _, config_path, opts, opts_rev = op
                    self._apply_load(node, config_path)
                    self.router.note_voice_loaded(node, vid)
                    with self._lock:
                        self.stats["ops_load"] += 1
                    log.info("placement: replayed voice %s onto node "
                             "%s", vid, node.node_id)
                    if opts is not None and self._apply_options is not None:
                        self._apply_options(node, opts)
                        with self._lock:
                            self._applied_opts[(node.index, vid)] = \
                                opts_rev
                            self.stats["ops_set_options"] += 1
                elif kind == "set_options":
                    if self._apply_options is None:
                        continue
                    _, _, opts, opts_rev = op
                    self._apply_options(node, opts)
                    with self._lock:
                        self._applied_opts[(node.index, vid)] = opts_rev
                        self.stats["ops_set_options"] += 1
                elif kind == "unload":
                    if self._apply_unload is None:
                        continue
                    # atomically stop routing the voice here FIRST
                    # (refused if a stream slipped in since the diff
                    # snapshot): the backend's UnloadVoice fails
                    # in-flight streams typed, so the RPC must never
                    # race a stream this router admitted.  A failed
                    # RPC self-heals — the next scrape restores the
                    # actual set and the op is retried.
                    if not self.router.begin_voice_retire(node, vid):
                        continue  # live streams arrived: next cycle
                    self._apply_unload(node, vid)
                    with self._lock:
                        self.stats["ops_unload"] += 1
                    log.info("placement: retired voice %s from node %s",
                             vid, node.node_id)
                applied.append((kind, vid))
            except Exception as e:
                with self._lock:
                    self.stats["op_failures"] += 1
                failures.append(f"{kind} {vid}: {type(e).__name__}: {e}")
        if failures:
            raise OperationError(
                f"placement: {len(failures)} reconcile op(s) failed on "
                f"node {node.node_id}: " + "; ".join(failures))
        return applied

    # -- introspection ---------------------------------------------------------
    def snapshot(self) -> dict:
        nodes = self.router.nodes
        by_index = {n.index: n for n in nodes}
        now = self._clock()
        with self._lock:
            assign = {vid: list(a) for vid, a in self._assign.items()}
            desired = {vid: dv for vid, dv in self._desired.items()}
            tombstones = sorted(self._tombstones)
            last_used = dict(self._last_used)
            stats = dict(self.stats)
        voices = []
        for vid, dv in sorted(desired.items()):
            assigned = [by_index[i].node_id for i in assign.get(vid, ())
                        if i in by_index]
            converged = [
                by_index[i].node_id for i in assign.get(vid, ())
                if i in by_index
                and by_index[i].loaded_voices is not None
                and vid in by_index[i].loaded_voices]
            voices.append({
                "voice_id": vid, "revision": dv.revision,
                "config_path": dv.config_path,
                "options_revision": (dv.options_revision
                                     if dv.options_payload is not None
                                     else None),
                "assigned": assigned, "converged": converged,
                "last_used_age_s": (
                    None if vid not in last_used
                    else round(now - last_used[vid], 3))})
        node_rows = []
        for n in nodes:
            placed = sorted(vid for vid, a in assign.items()
                            if n.index in a)
            node_rows.append({
                "node_id": n.node_id, "index": n.index,
                "placed": placed,
                "est_ram_mb": round(len(placed) * self.voice_mb, 3),
                "actual": (None if n.loaded_voices is None
                           else sorted(n.loaded_voices))})
        return {"replicas": self.replicas or "all",
                "reconcile_interval_s": self.reconcile_interval_s,
                "ram_budget_mb": self.ram_budget_mb,
                "voice_mb": self.voice_mb,
                "stats": stats, "voices": voices,
                "tombstones": tombstones, "nodes": node_rows}

    # -- metrics export --------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Attach the placement metric families.  Fixed-label counters
        bind now; per-voice gauge series appear lazily at
        :meth:`record_load` and are torn down exactly by
        :meth:`unregister_voice_series` (the fleetscope idiom)."""
        self._registry = registry
        for name, help in PLACEMENT_GAUGE_FAMILIES:
            self._families[name] = registry.gauge(name, help)
        ops = registry.counter(
            "sonata_placement_reconcile_ops_total",
            "Voice ops replayed by the anti-entropy reconciler, by op "
            "(load / unload / set_options).")
        for op in PLACEMENT_OPS:
            ops.labels(op=op).set_function(
                lambda o=op: float(self.stats.get("ops_" + o, 0)))
        ev = registry.counter(
            "sonata_placement_evictions_total",
            "Voice placements removed from a node, by reason "
            "(ram-budget = LRU under SONATA_PLACEMENT_RAM_BUDGET_MB; "
            "unplaced = the rebalancer replaced a dead or excess "
            "holder).")
        for reason in PLACEMENT_EVICTION_REASONS:
            ev.labels(reason=reason).set_function(
                lambda r=reason: float(self.stats.get(
                    "evictions_" + r.replace("-", "_"), 0)))

    def _ensure_voice_series(self, voice_id: str) -> None:
        if self._registry is None:
            return
        with self._series_lock:
            if voice_id in self._voice_series:
                return
            owned = self._voice_series.setdefault(voice_id, [])
            desired = self._families.get("sonata_placement_desired")
            if desired is not None:
                labels = {"voice": voice_id}
                desired.labels(**labels).set_function(
                    lambda v=voice_id: float(self.desired_count(v)))
                owned.append((desired, labels))
            conv = self._families.get("sonata_placement_converged")
            if conv is not None:
                labels = {"voice": voice_id}
                conv.labels(**labels).set_function(
                    lambda v=voice_id: float(self.converged_count(v)))
                owned.append((conv, labels))

    def _drop_voice_series(self, voice_id: str) -> None:
        with self._series_lock:
            for metric, labels in self._voice_series.pop(voice_id, []):
                metric.remove(**labels)

    def unregister_voice_series(self) -> None:
        """Drop every per-voice labeled series created at record_load
        (the teardown twin of the lazy registration)."""
        with self._series_lock:
            for owned in self._voice_series.values():
                for metric, labels in owned:
                    metric.remove(**labels)
            self._voice_series = {}

    def close(self) -> None:
        self.unregister_voice_series()
