"""Metrics registry and Prometheus text exposition over stdlib HTTP.

The serving stack already *measures* a lot — ``RtfCounter`` aggregates,
``dispatch_stats()`` counters, scheduler coalescing stats — but until now
the only way out was a log line every ~50 utterances.  This module gives
those numbers (plus the admission/deadline counters this subsystem adds)
a pull endpoint any Prometheus-compatible scraper understands:

- :class:`MetricsRegistry` owns named metrics.  Three kinds: ``counter``
  (monotonic), ``gauge`` (settable, or lazily computed via a callback at
  scrape time — how existing stats objects are wired in without adding a
  push call to every hot path), and ``histogram`` (bounded buckets, via
  :class:`~sonata_tpu.utils.profiling.Histogram`).
- Metrics are labelable (``metric.labels(voice="1234").inc()``); series
  for unloaded voices are removed with ``metric.remove(...)``.
- ``render()`` emits `text/plain; version=0.0.4` exposition format;
  :func:`parse_prometheus_text` is the matching validator used by the
  tests and the CI serving smoke.
- :func:`start_http_server` serves ``/metrics`` plus the health plane's
  ``/healthz`` and ``/readyz`` (see :mod:`.health`) from one tiny
  threaded stdlib ``http.server`` — no web framework dependency.

Port comes from ``SONATA_METRICS_PORT`` (0 = ephemeral; unset = no
server).
"""

from __future__ import annotations

import logging
import math
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from ..utils.profiling import Histogram

log = logging.getLogger("sonata.serving")

METRICS_PORT_ENV = "SONATA_METRICS_PORT"
METRICS_HOST_ENV = "SONATA_METRICS_HOST"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_LabelKey = Tuple[Tuple[str, str], ...]


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _label_str(labels: _LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Child:
    """One labeled series of a metric."""

    __slots__ = ("_value", "_fn", "_hist", "_lock")

    def __init__(self, hist_buckets=None, is_hist: bool = False):
        self._value = 0.0
        self._fn: Optional[Callable[[], Optional[float]]] = None
        self._hist = Histogram(hist_buckets) if is_hist else None
        self._lock = threading.Lock()

    # counter / gauge API
    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._fn = None

    def set_function(self, fn: Callable[[], Optional[float]]) -> None:
        """Compute the value at scrape time (returning None skips the
        series for that scrape)."""
        with self._lock:
            self._fn = fn

    def get(self) -> Optional[float]:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return fn()
        except Exception:
            # a dead callback (e.g. voice unloaded mid-scrape) must never
            # break the whole exposition
            return None

    # histogram API
    def observe(self, value: float) -> None:
        self._hist.observe(value)


class Metric:
    """A named metric family; series are created on first ``labels()``."""

    def __init__(self, name: str, help: str, type: str, buckets=None):
        self.name = name
        self.help = help
        self.type = type
        self._buckets = buckets
        self._children: Dict[_LabelKey, _Child] = {}
        self._lock = threading.Lock()

    def labels(self, **labels) -> _Child:
        key: _LabelKey = tuple(sorted(labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Child(self._buckets,
                               is_hist=self.type == "histogram")
                self._children[key] = child
            return child

    def remove(self, **labels) -> None:
        key: _LabelKey = tuple(sorted(labels.items()))
        with self._lock:
            self._children.pop(key, None)

    def attach(self, hist, **labels) -> None:
        """Expose an externally-owned
        :class:`~sonata_tpu.utils.profiling.Histogram` as this metric's
        series for ``labels`` — the histogram twin of a gauge callback:
        the owner (e.g. the batch scheduler's queue-wait histogram) keeps
        observing on its hot path, the scrape reads a snapshot."""
        if self.type != "histogram":
            raise ValueError(
                f"attach() needs a histogram metric, {self.name!r} is "
                f"{self.type}")
        key: _LabelKey = tuple(sorted(labels.items()))
        with self._lock:
            child = _Child()
            child._hist = hist
            self._children[key] = child

    # unlabeled convenience: metric.inc() == metric.labels().inc()
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def set_function(self, fn: Callable[[], Optional[float]]) -> None:
        self.labels().set_function(fn)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def get(self, **labels) -> Optional[float]:
        return self.labels(**labels).get()

    # -- exposition ----------------------------------------------------------
    def render(self) -> str:
        with self._lock:
            children = list(self._children.items())
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.type}"]
        n_series = 0
        for key, child in children:
            if self.type == "histogram":
                snap = child._hist.snapshot()
                for bound, cum in zip(snap.buckets, snap.counts):
                    le = 'le="' + _format_value(bound) + '"'
                    lines.append(
                        f"{self.name}_bucket{_label_str(key, le)} {cum}")
                inf = 'le="+Inf"'
                lines.append(f"{self.name}_bucket{_label_str(key, inf)} "
                             f"{snap.total}")
                lines.append(f"{self.name}_sum{_label_str(key)} "
                             f"{_format_value(snap.sum)}")
                lines.append(f"{self.name}_count{_label_str(key)} "
                             f"{snap.total}")
                n_series += 1
                continue
            value = child.get()
            if value is None:
                continue
            lines.append(f"{self.name}{_label_str(key)} "
                         f"{_format_value(value)}")
            n_series += 1
        if n_series == 0:
            return ""
        return "\n".join(lines) + "\n"


class MetricsRegistry:
    """Named metric families, rendered together in one exposition."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, help: str, type: str,
                  buckets=None) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.type != type:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.type}")
                return existing
            m = Metric(name, help, type, buckets=buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str) -> Metric:
        return self._register(name, help, "counter")

    def gauge(self, name: str, help: str) -> Metric:
        return self._register(name, help, "gauge")

    def histogram(self, name: str, help: str, buckets=None) -> Metric:
        return self._register(name, help, "histogram", buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return "".join(m.render() for m in metrics)


def _unescape_label(v: str) -> str:
    """Invert :func:`_escape_label` (``\\\\`` ``\\n`` ``\\"``), so parsed
    label values round-trip to exactly what ``labels(...)`` was given."""
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                             "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prometheus_text(text: str) -> Dict[str, list]:
    """Strict-enough exposition parser: ``{series_name: [(labels, value)]}``.

    Raises ``ValueError`` on malformed lines.  Used by the tests and the
    CI serving smoke to assert ``render()`` output actually parses —
    the exporter ships with its own format check.  Label values are
    unescaped, so ``render()`` → ``parse`` round-trips exactly.
    """
    import re

    series: Dict[str, list] = {}
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(\{([^}]*)\})?'
        r'\s+(-?[0-9.eE+-]+|NaN|[+-]Inf)$')
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        if line.startswith("#"):
            raise ValueError(f"line {lineno}: bad comment {line!r}")
        m = sample_re.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name, _, labelblock, raw = m.groups()
        labels = {}
        if labelblock:
            consumed = label_re.sub("", labelblock).strip(", \t")
            if consumed:
                raise ValueError(
                    f"line {lineno}: bad label syntax {labelblock!r}")
            labels = {k: _unescape_label(v)
                      for k, v in label_re.findall(labelblock)}
        if raw == "+Inf":
            value = math.inf
        elif raw == "-Inf":
            value = -math.inf
        elif raw == "NaN":
            value = math.nan
        else:
            value = float(raw)
        series.setdefault(name, []).append((labels, value))
    return series


# ---------------------------------------------------------------------------
# HTTP plane: /metrics + /healthz + /readyz on one stdlib server
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    # set per-server via type() in start_http_server
    registry: MetricsRegistry = None
    health = None
    tracer = None
    scope = None
    fleet = None
    tenancy = None
    ledger = None

    def do_GET(self):  # noqa: N802 (http.server API)
        from . import faults

        path, _, query = self.path.partition("?")
        if path == "/metrics":
            try:
                faults.fire("metrics.scrape")
            except faults.InjectedFault as e:
                # an injected scrape fault degrades exactly one scrape —
                # the handler thread answers 503 and the server lives on
                self._reply(503, f"{e}\n".encode())
                return
            body = self.registry.render().encode("utf-8")
            self._reply(200, body, CONTENT_TYPE)
        elif path == "/healthz":
            live = self.health is None or self.health.live
            self._reply(200 if live else 503,
                        b"ok\n" if live else b"unhealthy\n")
        elif path == "/readyz":
            # the node tag names this process in fleet-side probe logs
            # (the sonata-mesh router scrapes /readyz for membership)
            nid = getattr(self.health, "node_id", None)
            tag = f"node={nid}\n".encode() if nid else b""
            # the loaded-voice set is the placement reconciler's
            # ACTUAL state — emitted even when empty (a restarted
            # node's empty set is exactly the news that triggers the
            # replay), on both the 200 and 503 bodies (a warming node
            # already holds its voices)
            voices_view = getattr(self.health, "voices_view", None)
            if voices_view is not None:
                tag += ("voices=" + ",".join(voices_view())
                        + "\n").encode()
            if self.health is None or self.health.ready:
                self._reply(200, b"ready\n" + tag)
            else:
                reason = (self.health.reason or "not ready").encode()
                self._reply(503, b"not ready: " + reason + b"\n" + tag)
        elif path in ("/debug/traces", "/debug/slowest"):
            self._reply_traces(path, query)
        elif path == "/debug/profile":
            self._reply_profile(query)
        elif path == "/debug/failpoints":
            self._reply_failpoints(query)
        elif path in ("/debug/quantiles", "/debug/buckets",
                      "/debug/timeline", "/debug/scope/export"):
            self._reply_scope(path, query)
        elif path in ("/debug/fleet", "/debug/traces/stitched"):
            self._reply_fleet(path, query)
        elif path == "/debug/tenants":
            self._reply_tenants()
        elif path == "/debug/requests":
            self._reply_requests(query)
        else:
            self._reply(404, b"not found\n")

    def do_POST(self):  # noqa: N802 (http.server API)
        """``POST /debug/tenants`` — the mesh router's desired-state
        tenant-config push (sonata-tenancy): a revisioned table the
        node plane applies idempotently.  404 on tenancy-off processes
        (enabling tenancy stays the node operator's call — the router
        only synchronizes tables, it cannot switch the feature on)."""
        import json

        path, _, _ = self.path.partition("?")
        if path != "/debug/tenants":
            self._reply(404, b"not found\n")
            return
        if self.tenancy is None:
            self._reply(404, b"tenancy not enabled on this server\n")
            return
        try:
            length = int(self.headers.get("Content-Length", "0") or 0)
            doc = json.loads(self.rfile.read(length).decode("utf-8"))
            applied = self.tenancy.apply_remote(doc)
        except (ValueError, UnicodeDecodeError) as e:
            self._reply(400, (str(e) + "\n").encode())
            return
        body = json.dumps({"applied": applied,
                           "revision": self.tenancy.revision,
                           "remote_revision":
                               self.tenancy.remote_revision})
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    # -- tenant control plane (serving/tenancy.py) ---------------------------
    def _reply_tenants(self) -> None:
        """``GET /debug/tenants``: the tenant table + per-tenant
        counters/queue state.  Same gate as the scope/tracer siblings:
        tenancy off, no surface."""
        import json

        if self.tenancy is None:
            self._reply(404, b"tenancy not enabled on this server\n")
            return
        body = json.dumps(self.tenancy.snapshot())
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    # -- aggregation plane (serving/scope.py) --------------------------------
    def _reply_scope(self, path: str, query: str) -> None:
        """``/debug/quantiles`` (rolling per-stage quantiles + SLO
        state), ``/debug/buckets`` (dispatch padding-waste tables),
        ``/debug/timeline`` (flight-recorder ring; ``?format=chrome``
        for counter tracks)."""
        import json
        from urllib.parse import parse_qs

        if self.scope is None:
            # same posture as the tracer-gated /debug siblings: no
            # aggregation plane configured, no debug surface
            self._reply(404, b"scope not enabled on this server\n")
            return
        if path == "/debug/scope/export":
            # the fleet hop (ISSUE 13): the whole aggregation plane as
            # a compact mergeable payload, tagged with this node's id
            doc = self.scope.export_snapshot()
            doc["node_id"] = getattr(self.health, "node_id", None)
            body = json.dumps(doc)
        elif path == "/debug/quantiles":
            body = json.dumps({**self.scope.quantiles_snapshot(),
                               **self.scope.slo_snapshot()})
        elif path == "/debug/buckets":
            body = json.dumps(self.scope.buckets_snapshot())
        else:
            params = parse_qs(query)
            if params.get("format", [""])[0] == "chrome":
                body = json.dumps(self.scope.timeline_chrome())
            else:
                snaps = self.scope.timeline_snapshot()
                body = json.dumps({
                    "count": len(snaps),
                    "interval_s": self.scope.tick_interval_s,
                    "snapshots": snaps})
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    # -- fleet aggregation plane (serving/fleetscope.py) ---------------------
    def _reply_fleet(self, path: str, query: str) -> None:
        """``/debug/fleet`` (the fleet scoreboard) and
        ``/debug/traces/stitched?id=`` (router + serving-node spans in
        one Chrome-trace document).  Router-only surfaces: 404 on
        servers with no fleet plane, same gate as the scope/tracer
        siblings."""
        import json
        from urllib.parse import parse_qs

        if self.fleet is None:
            self._reply(404, b"fleet aggregation not enabled on this "
                             b"server\n")
            return
        if path == "/debug/fleet":
            code, doc = 200, self.fleet.fleet_snapshot()
        else:
            params = parse_qs(query)
            code, doc = self.fleet.stitched_trace(
                params.get("id", [""])[0])
        self._reply(code, json.dumps(doc).encode("utf-8"),
                    "application/json; charset=utf-8")

    # -- failpoint arming plane (serving/faults.py) --------------------------
    def _reply_failpoints(self, query: str) -> None:
        """``GET /debug/failpoints`` — no params: JSON state snapshot;
        ``?arm=site:mode[:rate[:latency_ms[:max_hits]]]`` (repeatable)
        arms; ``?disarm=site`` / ``?disarm=all`` disarms (releasing the
        threads stuck in the disarmed sites' ``hang``)."""
        import json
        from urllib.parse import parse_qs

        from . import faults

        params = parse_qs(query)
        wants_mutation = bool(params.get("arm") or params.get("disarm"))
        if wants_mutation and not faults.http_arming_allowed():
            # same posture as the tracer-gated /debug siblings: a metrics
            # port reachable cluster-wide must not double as a remote
            # fault-injection switch without an explicit opt-in
            self._reply(403, b"failpoint arming not enabled on this "
                             b"server (set SONATA_FAILPOINTS or call "
                             b"faults.enable_http_arming())\n")
            return
        try:
            for spec in params.get("arm", []):
                faults.registry().arm_spec(spec)
            for site in params.get("disarm", []):
                if site == "all":
                    faults.registry().disarm_all()
                else:
                    faults.registry().disarm(site)
        except ValueError as e:
            self._reply(400, (str(e) + "\n").encode())
            return
        body = json.dumps(faults.registry().snapshot(), indent=2,
                          sort_keys=True)
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    # -- request-trace debug plane (serving/tracing.py) ----------------------
    def _reply_traces(self, path: str, query: str) -> None:
        import json
        from urllib.parse import parse_qs

        if self.tracer is None:
            self._reply(404, b"tracing not enabled on this server\n")
            return
        params = parse_qs(query)
        traces = (self.tracer.slowest_traces() if path == "/debug/slowest"
                  else self.tracer.recent_traces())
        wanted_id = params.get("id", [""])[0]
        if wanted_id:
            # exact-id lookup: what the mesh router's stitched-trace
            # fetch uses to pull one node trace instead of the ring
            traces = [t for t in traces if t.request_id == wanted_id]
        try:
            limit = int(params.get("limit", ["0"])[0])
        except ValueError:
            limit = 0
        if limit > 0:
            traces = traces[:limit]
        if params.get("format", [""])[0] == "chrome":
            body = json.dumps(self.tracer.chrome_trace(traces))
        else:
            body = json.dumps({
                "count": len(traces),
                "order": ("slowest-first" if path == "/debug/slowest"
                          else "newest-first"),
                "traces": [t.to_dict() for t in traces]})
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    def _reply_profile(self, query: str) -> None:
        import json
        from urllib.parse import parse_qs

        from ..utils.profiling import capture_profile

        if self.tracer is None:
            # same gate as /debug/traces: no tracer, no debug plane — a
            # device capture blocks a handler thread and writes to disk,
            # which an operator who disabled tracing did not sign up for
            self._reply(404, b"tracing not enabled on this server\n")
            return
        params = parse_qs(query)
        try:
            seconds = float(params.get("seconds", ["2"])[0])
        except ValueError:
            self._reply(400, b"seconds must be a number\n")
            return
        try:
            log_dir = capture_profile(seconds)
        except RuntimeError as e:  # capture already running
            self._reply(409, (str(e) + "\n").encode())
            return
        except Exception as e:  # jax profiler unavailable on this build
            self._reply(503, f"profiler capture failed: {e}\n".encode())
            return
        body = json.dumps({"log_dir": log_dir, "seconds": seconds,
                           "view": "tensorboard --logdir <log_dir> "
                                   "(or load into Perfetto/XProf)"})
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    # -- request ledger (serving/ledger.py) ----------------------------------
    def _reply_requests(self, query: str) -> None:
        """``GET /debug/requests`` — the wide-event ring, filterable by
        ``tenant=&voice=&outcome=&since=&id=&limit=`` (newest first).
        ``id=`` on a mesh router also merges the serving node's own
        record into the hop record (the stitched-trace pattern).  404
        on ledger-off processes, like the scope/tracer siblings."""
        import json
        from urllib.parse import parse_qs

        if self.ledger is None:
            self._reply(404, b"ledger not enabled on this server\n")
            return
        params = parse_qs(query)

        def first(key):
            return params.get(key, [""])[0] or None

        since = first("since")
        if since is not None:
            try:
                since = float(since)
            except ValueError:
                self._reply(400, b"since must be a unix timestamp\n")
                return
        try:
            limit = int(first("limit") or 100)
        except ValueError:
            self._reply(400, b"limit must be an integer\n")
            return
        records = self.ledger.query(
            tenant=first("tenant"), voice=first("voice"),
            outcome=first("outcome"), since=since,
            request_id=first("id"), limit=limit)
        body = json.dumps({"count": len(records), "records": records})
        self._reply(200, body.encode("utf-8"),
                    "application/json; charset=utf-8")

    def _reply(self, code: int, body: bytes,
               content_type: str = "text/plain; charset=utf-8") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes every few seconds —
        log.debug("metrics http: " + fmt, *args)  # keep them off INFO


class MetricsHTTPServer:
    """Owns the background thread serving the metrics/health plane."""

    def __init__(self, server: ThreadingHTTPServer):
        self._server = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(target=server.serve_forever,
                                        name="sonata_metrics_http",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


def resolve_metrics_port(port: Optional[int] = None) -> Optional[int]:
    """Explicit port wins; else ``SONATA_METRICS_PORT``; else disabled.

    Returns None when no metrics server should start (0 is a valid
    request: bind an ephemeral port)."""
    if port is not None:
        return port
    raw = os.environ.get(METRICS_PORT_ENV)
    if raw is None or raw.strip() == "":
        return None
    try:
        return int(raw)
    except ValueError:
        log.warning("ignoring non-integer %s=%r", METRICS_PORT_ENV, raw)
        return None


def start_http_server(registry: MetricsRegistry, health=None,
                      port: Optional[int] = None,
                      host: Optional[str] = None,
                      tracer=None, scope=None,
                      fleet=None, tenancy=None,
                      ledger=None) -> MetricsHTTPServer:
    """Serve ``/metrics``, ``/healthz``, ``/readyz`` — plus, when a
    :class:`~sonata_tpu.serving.tracing.Tracer` is given,
    ``/debug/traces``, ``/debug/slowest``, and ``/debug/profile``; when
    a :class:`~sonata_tpu.serving.scope.Scope` is given,
    ``/debug/quantiles``, ``/debug/buckets``, ``/debug/timeline``, and
    ``/debug/scope/export``; and, when a
    :class:`~sonata_tpu.serving.fleetscope.FleetScope` is given (mesh
    routers), ``/debug/fleet`` and ``/debug/traces/stitched`` — in a
    daemon thread."""
    host = host or os.environ.get(METRICS_HOST_ENV, "127.0.0.1")
    handler = type("BoundHandler", (_Handler,),
                   {"registry": registry, "health": health,
                    "tracer": tracer, "scope": scope, "fleet": fleet,
                    "tenancy": tenancy, "ledger": ledger})
    httpd = ThreadingHTTPServer((host, port or 0), handler)
    httpd.daemon_threads = True
    return MetricsHTTPServer(httpd)
