"""First-party failpoint injection: named fault sites, armed on demand.

The serving stack's failure machinery (breakers, resubmission, deadline
drops, readiness gates) has so far been proven only by ad-hoc
monkeypatching inside individual tests — nothing can arm a fault against
a *running* server, nothing covers a dispatch that *hangs* rather than
raises, and no two chaos runs ever see the same fault schedule.  This
module is the repo's answer: a registry of **named injection sites**
compiled into the serving hot paths, each a single-branch no-op until an
operator or test arms it.

Sites (the canonical list — the sonata-lint ``failpoints`` pass checks
that every name armed anywhere exists here and that every site is
exercised by at least one test):

- ``dispatch.device_call`` — around ``speak_batch`` inside a device
  dispatch (fired on the dispatch thread, inside the breaker wrapper on
  pool replicas so injected errors count toward the breaker);
- ``scheduler.gather``    — the batch scheduler's worker gather loop;
- ``pool.route``          — replica-pool routing, request side;
- ``phonemize``           — the G2P entry every stream mode funnels through;
- ``warmup``              — the readiness-gating warmup synthesis;
- ``metrics.scrape``      — the ``/metrics`` exposition handler;
- ``mesh.route``          — inside every per-node dispatch attempt of the
  sonata-mesh routing tier (an injected fault counts toward that node's
  breaker exactly like a real one);
- ``mesh.health``         — inside every mesh membership health probe
  (how the chaos lane kills/wedges/partitions a whole node
  deterministically without owning real processes);
- ``mesh.reconcile``      — inside every voice-placement reconcile cycle
  (an injected error counts toward that node's breaker on its own
  consecutive reconcile-failure counter — separate, so probe successes
  cannot launder it; a hang stalls only that node's prober thread);
- ``mesh.cache_affinity`` — inside the mesh router's cache-key
  derivation / affinity pick (``serving/fleetcache.py``): an injected
  error degrades that request to plain least-outstanding routing — a
  broken affinity tier can never fail a request;
- ``cache.lookup``        — inside every synthesis-cache probe
  (``serving/synthcache.py``): an injected error degrades that lookup
  to a normal miss — a broken cache can never fail a request;
- ``ledger.emit``         — inside every request-ledger record finalize
  (``serving/ledger.py``): an injected error degrades that finalize to
  no-record — a broken ledger can never fail a request.

Modes:

- ``error``         — raise :class:`InjectedFault` (an ``OperationError``,
  so frontends map it like any operation failure);
- ``hang``          — block (the wedged-chip simulation: no exception, no
  return) until the site is disarmed or the per-arm ``latency_ms``
  cap expires — the scenario the hung-dispatch watchdog exists for;
- ``slow``          — sleep ``latency_ms`` (default 100), then continue;
- ``corrupt-shape`` — return the action string so shape-aware call sites
  (the dispatch path) drop a row from the device result, breaking the
  results-per-request invariant downstream.

Arming — env at process start, endpoint at runtime, or programmatic:

- ``SONATA_FAILPOINTS=site:mode[:rate[:latency_ms[:max_hits]]]`` (comma
  separated for several sites; read when the registry is first touched);
- ``GET /debug/failpoints?arm=spec`` / ``?disarm=site|all`` on the
  metrics plane (no params = JSON state snapshot);
- :func:`registry` ``.arm(...)`` / ``.disarm(...)`` from tests.

**Determinism.**  Whether hit *n* of a site fires is a pure function of
``(SONATA_FAILPOINT_SEED, site, n, rate)`` — a blake2b draw, not a live
PRNG — so a chaos run replays exactly given the same request order (the
chaos smoke pins two seeds in CI).  ``max_hits`` bounds how many times an
arm fires before it is spent (e.g. hang exactly one dispatch).

**Overhead.**  :func:`fire` is the only hot-path surface; with nothing
armed it reads one module-level bool and returns — the chaos smoke
measures this stays in the noise (same bar as tracing's
``trace_overhead`` row in BENCH_STREAMING_CPU_r09).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Dict, Optional

from ..core import OperationError
from . import tracing

log = logging.getLogger("sonata.serving")

FAILPOINTS_ENV = "SONATA_FAILPOINTS"
SEED_ENV = "SONATA_FAILPOINT_SEED"

#: canonical injection sites; arming any other name is a ValueError (and
#: a sonata-lint ``failpoints`` finding at the call site)
SITES = (
    "dispatch.device_call",
    "scheduler.gather",
    "pool.route",
    "phonemize",
    "warmup",
    "metrics.scrape",
    "mesh.route",
    "mesh.health",
    "mesh.reconcile",
    "mesh.cache_affinity",
    "cache.lookup",
    "tenancy.classify",
    "ledger.emit",
)

MODES = ("error", "hang", "slow", "corrupt-shape")

DEFAULT_SLOW_MS = 100.0
#: a hang with no explicit cap still ends eventually — a leaked
#: quarantined thread must not outlive any plausible test or incident
DEFAULT_HANG_CAP_S = 600.0


class InjectedFault(OperationError):
    """A failpoint fired in ``error`` mode (or a hang hit its cap)."""


class _Arm:
    """One armed site's state (mutated under the registry lock)."""

    __slots__ = ("site", "mode", "rate", "latency_ms", "max_hits",
                 "hits", "fires", "release")

    def __init__(self, site: str, mode: str, rate: float,
                 latency_ms: Optional[float], max_hits: Optional[int]):
        self.site = site
        self.mode = mode
        self.rate = rate
        self.latency_ms = latency_ms
        self.max_hits = max_hits
        self.hits = 0    # decisions evaluated (the deterministic index)
        self.fires = 0   # times the fault actually fired
        #: per-arm hang release: threads blocked in this arm's ``hang``
        #: capture THIS event, so disarming one site frees its waiters
        #: without waking hangs armed at other sites (re-arming builds a
        #: fresh _Arm, so a released old arm cannot leak into the new one)
        self.release = threading.Event()

    def snapshot(self) -> dict:
        return {"mode": self.mode, "rate": self.rate,
                "latency_ms": self.latency_ms, "max_hits": self.max_hits,
                "hits": self.hits, "fires": self.fires,
                "spent": (self.max_hits is not None
                          and self.fires >= self.max_hits)}


def _decide(seed: int, site: str, n: int, rate: float) -> bool:
    """Deterministic fire decision for hit ``n`` of ``site``."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    digest = hashlib.blake2b(f"{seed}:{site}:{n}".encode(),
                             digest_size=8).digest()
    return int.from_bytes(digest, "big") / 2.0 ** 64 < rate


class FailpointRegistry:
    """Armed-site table plus the hang release used to free stuck threads."""

    def __init__(self, seed: Optional[int] = None):
        self._lock = threading.Lock()
        self._arms: Dict[str, _Arm] = {}
        #: lifetime fire counts per site — survive disarm, so the metrics
        #: plane and the chaos smoke can assert on a finished schedule
        self._fires_total: Dict[str, int] = {}
        if seed is None:
            try:
                seed = int(os.environ.get(SEED_ENV, "0"))
            except ValueError:
                seed = 0
        self.seed = seed

    # -- arming ---------------------------------------------------------------
    def arm(self, site: str, mode: str, rate: float = 1.0,
            latency_ms: Optional[float] = None,
            max_hits: Optional[int] = None) -> None:
        if site not in SITES:
            raise ValueError(
                f"unknown failpoint site {site!r} (registry: "
                f"{', '.join(SITES)})")
        if mode not in MODES:
            raise ValueError(
                f"unknown failpoint mode {mode!r} (modes: "
                f"{', '.join(MODES)})")
        with self._lock:
            old = self._arms.get(site)
            self._arms[site] = _Arm(site, mode, float(rate), latency_ms,
                                    max_hits)
        if old is not None:
            old.release.set()  # the replaced arm's hangs proceed normally
        self._sync_active()
        log.warning("failpoint armed: %s mode=%s rate=%g latency_ms=%s "
                    "max_hits=%s seed=%d", site, mode, rate, latency_ms,
                    max_hits, self.seed)

    def arm_spec(self, spec: str) -> None:
        """Arm from one ``site:mode[:rate[:latency_ms[:max_hits]]]``."""
        parts = spec.strip().split(":")
        if len(parts) < 2 or len(parts) > 5:
            raise ValueError(
                f"bad failpoint spec {spec!r} "
                "(site:mode[:rate[:latency_ms[:max_hits]]])")
        site, mode = parts[0], parts[1]
        try:
            rate = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
            latency = (float(parts[3])
                       if len(parts) > 3 and parts[3] else None)
            hits = int(parts[4]) if len(parts) > 4 and parts[4] else None
        except ValueError:
            raise ValueError(f"bad failpoint spec {spec!r}: rate/"
                             "latency_ms/max_hits must be numeric") from None
        self.arm(site, mode, rate=rate, latency_ms=latency, max_hits=hits)

    def arm_from_env(self) -> int:
        """Arm every spec in ``SONATA_FAILPOINTS``; returns the count."""
        raw = os.environ.get(FAILPOINTS_ENV, "").strip()
        if not raw:
            return 0
        n = 0
        for spec in raw.split(","):
            if spec.strip():
                self.arm_spec(spec)
                n += 1
        return n

    def disarm(self, site: str) -> None:
        """Disarm one site and release any thread hung at it (threads
        hung at OTHER still-armed sites keep waiting)."""
        with self._lock:
            arm = self._arms.pop(site, None)
        if arm is not None:
            arm.release.set()
        self._sync_active()
        log.warning("failpoint disarmed: %s", site)

    def disarm_all(self) -> None:
        """Disarm every site and release any thread stuck in a hang."""
        with self._lock:
            arms = list(self._arms.values())
            self._arms.clear()
        for arm in arms:
            arm.release.set()  # wake hung threads on the event they captured
        self._sync_active()
        log.warning("failpoints disarmed (all); hung threads released")

    def _sync_active(self) -> None:
        """Refresh the module-level fire() fast-path flag — but only
        when *this* is the process-global registry: a private instance
        (tests build their own) must not flip chaos on or off for the
        whole process."""
        if _registry is self:
            _set_active(bool(self._arms))

    # -- introspection --------------------------------------------------------
    def snapshot(self) -> dict:
        # copy under the lock, render outside it: snapshot() must call
        # nothing while holding _lock (introspection can be called while
        # other subsystems hold their own locks)
        with self._lock:
            arms = dict(self._arms)
            fires = dict(self._fires_total)
        return {"seed": self.seed,
                "armed": {s: a.snapshot() for s, a in arms.items()},
                "fires_total": fires,
                "sites": list(SITES)}

    def fires_total(self, site: str) -> int:
        with self._lock:
            return self._fires_total.get(site, 0)

    # -- firing ---------------------------------------------------------------
    def fire(self, site: str) -> Optional[str]:
        """Evaluate ``site``; act out the armed mode when it fires.

        Returns the action string for modes the *caller* must apply
        (``corrupt-shape``), else None.  All decision state is updated
        under the lock; the act itself (sleep / hang / raise) happens
        outside it.
        """
        with self._lock:
            arm = self._arms.get(site)
            if arm is None:
                return None
            if arm.max_hits is not None and arm.fires >= arm.max_hits:
                return None
            n = arm.hits
            arm.hits += 1
            if not _decide(self.seed, site, n, arm.rate):
                return None
            arm.fires += 1
            self._fires_total[site] = self._fires_total.get(site, 0) + 1
            mode, latency = arm.mode, arm.latency_ms
            release = arm.release
        return self._act(site, mode, latency, release)

    def _act(self, site: str, mode: str, latency_ms: Optional[float],
             release: threading.Event) -> Optional[str]:
        with tracing.span("failpoint", site=site, mode=mode):
            if mode == "error":
                raise InjectedFault(
                    f"injected fault at failpoint {site} (mode=error, "
                    f"seed={self.seed})")
            if mode == "slow":
                time.sleep((latency_ms if latency_ms is not None
                            else DEFAULT_SLOW_MS) / 1e3)
                return None
            if mode == "hang":
                # the wedged-device simulation: block with no exception
                # until this site is disarmed (or re-armed); the cap
                # turns an abandoned hang into a loud error instead of a
                # thread leaked forever
                cap_s = (latency_ms / 1e3 if latency_ms is not None
                         else DEFAULT_HANG_CAP_S)
                if release.wait(timeout=cap_s):
                    return None  # released by disarm: proceed normally
                raise InjectedFault(
                    f"injected hang at failpoint {site} exceeded its "
                    f"{cap_s:g}s cap without being disarmed")
            return mode  # corrupt-shape: caller applies it


# ---------------------------------------------------------------------------
# process-global registry + the single-branch hot-path hook
# ---------------------------------------------------------------------------

#: hot-path fast flag: fire() reads this one bool and returns when
#: nothing is armed anywhere in the process
_ACTIVE = False

_registry: Optional[FailpointRegistry] = None
_registry_lock = threading.Lock()


def _set_active(value: bool) -> None:
    global _ACTIVE
    _ACTIVE = value


def registry() -> FailpointRegistry:
    """The process-wide registry (created on first use; arms any
    ``SONATA_FAILPOINTS`` specs present in the environment)."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                reg = FailpointRegistry()
                reg.arm_from_env()   # _sync_active no-ops: not global yet
                _registry = reg
                reg._sync_active()
    return _registry


def fire(site: str) -> Optional[str]:
    """The injection hook call sites compile in: a no-op single branch
    until something is armed."""
    if not _ACTIVE:
        return None
    return registry().fire(site)


def corrupt_result(action: Optional[str], rows):
    """Apply a ``corrupt-shape`` firing to a device result: drop the
    trailing row so the caller's row-count check trips loudly.  The one
    place the corruption contract lives — both dispatch paths (the
    pool's breaker wrapper and the bare-model scheduler) call this."""
    if action == "corrupt-shape":
        return list(rows)[:-1]
    return rows


def fires_total(site: str) -> Optional[float]:
    """Lifetime fire count for a site, or None while no registry exists
    (keeps the metrics series absent until chaos tooling shows up)."""
    reg = _registry
    if reg is None:
        return None
    return float(reg.fires_total(site))


#: programmatic opt-in for the HTTP arming plane (chaos tooling and
#: tests that boot a server without touching the environment)
_HTTP_ARMING = False


def enable_http_arming(value: bool = True) -> None:
    """Opt this process into ``/debug/failpoints`` arm/disarm requests."""
    global _HTTP_ARMING
    _HTTP_ARMING = value


def http_arming_allowed() -> bool:
    """Whether ``/debug/failpoints`` may mutate the registry.  Requires
    an explicit opt-in — ``SONATA_FAILPOINTS`` present in the
    environment (even empty: the operator consciously enabled the chaos
    plane) or :func:`enable_http_arming` — so a production metrics port
    is never a remote fault-injection switch."""
    return _HTTP_ARMING or FAILPOINTS_ENV in os.environ


def warn_if_armed(logger: logging.Logger) -> None:
    """Log the loud chaos banner when ``SONATA_FAILPOINTS`` is set —
    shared by every frontend: a process accidentally started with armed
    failpoints is a production incident waiting to be misdiagnosed.
    Present-but-empty gets its own banner: that form arms nothing but
    still opens the HTTP arming plane (:func:`http_arming_allowed`),
    which must never happen silently."""
    if os.environ.get(FAILPOINTS_ENV):
        logger.warning("failpoints armed from the environment: %s",
                       registry().snapshot()["armed"])
    elif FAILPOINTS_ENV in os.environ:
        logger.warning("SONATA_FAILPOINTS is present (empty): no sites "
                       "armed, but /debug/failpoints arming is ENABLED "
                       "on the metrics port")


# arm at import when the env asks for it: frontends import the serving
# package long before the first request, so env-armed chaos runs never
# depend on which code path first calls fire()
if os.environ.get(FAILPOINTS_ENV, "").strip():
    registry()
