"""sonata-tenancy: multi-tenant admission, weighted-fair QoS, and
per-tenant accounting across node and fleet.

The serving stack federates routing (sonata-mesh), observability
(sonata-fleetscope), voice placement (sonata-placement), and the
synthesis cache (sonata-synthcache/fleetcache) — but until this module
the admission plane treated all traffic as one anonymous stream: one
tenant's burst deepened EVERY tenant's queue wait.  This module is the
tenant control plane:

- **Identity.**  Requests carry ``x-tenant-id`` metadata (unlabeled
  traffic lands in the ``default`` tenant — wire-compatible: no proto
  change, no client change).  Unknown tenant ids also land in
  ``default`` so a client-controlled header can never mint unbounded
  metric label cardinality.
- **Config.**  ``SONATA_TENANTS`` (inline JSON if the value starts
  with ``{``, else a file path) maps tenant name → ``{weight, qps,
  burst, cache_share, shed_priority}``.  The table is hot-reloadable:
  the plane re-stats the file (or re-reads the env value) at most every
  ``SONATA_TENANTS_RELOAD_S`` seconds and swaps the table in place —
  no restart, buckets of unchanged tenants keep their fill.  Unset ⇒
  :func:`from_env` returns None and every request path is byte-for-byte
  the pre-tenancy shape (pinned by tests/test_tenancy.py).
- **Quota.**  Per-tenant token buckets (``qps`` refill, ``burst``
  capacity, 0 = unlimited) charged at the node frontend AFTER the
  synthesis-cache probe — a cache hit costs no device time and must not
  burn quota.  A refusal is typed RESOURCE_EXHAUSTED with a
  ``retry-after-s`` trailer, computed from the bucket's actual deficit.
- **Weighted fairness.**  :class:`FairGate` — deficit round robin (DRR)
  over per-tenant FIFOs — gates stream entry into the synthesis engine.
  Below saturation every stream enters immediately (zero added latency);
  at saturation each tenant queues in ITS OWN FIFO and grants are dealt
  in weight proportion, so a bursting tenant deepens only its own queue.
- **Shed ladder rung.**  Under degradation (the PR-6 ladder), the
  over-quota / lowest-priority tenant is shed FIRST (typed, counted via
  ``sonata_tenant_shed_total``) before any fleet-wide shed: at level >= 1
  background tenants (``shed_priority`` > 0) shed; at level >= 2 any
  tenant whose bucket is empty sheds.
- **Router tier.**  When fleet-deployed the mesh router runs its own
  plane (one tenant, N backends — quota state belongs where the fleet
  view is), charges quota at ``_routed_stream``, and stamps
  ``x-sonata-tenant`` + ``x-sonata-tenant-quota: router`` on the
  backend hop so nodes skip double-charging (router wins; per-node
  buckets are the router-absent fallback).  The router's config table
  propagates to node planes as desired state — a revisioned document
  POSTed to each node's ``/debug/tenants`` riding the prober threads
  (:class:`ConfigPropagator`, the placement registry's pattern: the
  router re-pushes until the node acks the revision, so a restarted
  node converges with zero operator action).
- **Failure posture.**  The ``tenancy.classify`` failpoint wraps
  identity extraction: an injected (or real) classification error
  degrades the request to the ``default`` tenant — served, counted
  (``sonata_tenancy_classify_errors_total``), never refused.

Tenancy deliberately does NOT join the synthesis-cache key: identical
text across tenants still dedups to one entry (and fleetcache affinity
keys are unchanged).  What IS per-tenant in the cache is the *insert
budget*: see ``SynthCache`` owner accounting (``cache_share``).

Nothing here imports gRPC or jax.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque, namedtuple
from typing import Callable, Dict, Optional

from . import faults

log = logging.getLogger("sonata.serving")

TENANTS_ENV = "SONATA_TENANTS"
RELOAD_S_ENV = "SONATA_TENANTS_RELOAD_S"

#: the client-facing identity header (wire-compatible: plain metadata)
TENANT_HEADER = "x-tenant-id"
#: the router→node hop headers: the router's classification and the
#: marker that quota was already charged at the router tier
ROUTER_TENANT_HEADER = "x-sonata-tenant"
ROUTER_ENFORCED_HEADER = "x-sonata-tenant-quota"
ROUTER_ENFORCED_VALUE = "router"
#: the typed-refusal trailer carrying the bucket's actual deficit
RETRY_AFTER_TRAILER = "retry-after-s"

DEFAULT_TENANT = "default"
DEFAULT_RELOAD_S = 2.0

#: tenant-labeled counter families, registered table-driven in
#: :meth:`TenantPlane.bind_metrics` (the sonata-lint metricsdoc pass
#: resolves loop-registered literal tables); series are created lazily
#: per tenant and torn down exactly by :meth:`TenantPlane.
#: unregister_tenant_series` (the fleetscope idiom)
TENANT_COUNTER_FAMILIES = (
    ("sonata_tenant_admitted_total",
     "Requests admitted past node admission, by tenant (cache hits "
     "included — admission is cheaper than synthesis, quota is not "
     "charged for hits)."),
    ("sonata_tenant_quota_rejections_total",
     "Requests refused RESOURCE_EXHAUSTED by the tenant's token "
     "bucket (retry-after-s trailer carries the bucket deficit)."),
    ("sonata_tenant_shed_total",
     "Requests shed by the per-tenant degradation rung (the noisy / "
     "background tenant sheds before any fleet-wide shed)."),
)
TENANT_GAUGE_FAMILIES = (
    ("sonata_tenant_queue_depth",
     "Streams waiting in the tenant's own weighted-fair FIFO for a "
     "synthesis slot (a bursting tenant deepens only its own queue)."),
)

#: one classified request identity: the tenant name plus whether the
#: mesh router already charged quota for this hop (node buckets then
#: skip the charge — router wins, per-node is the fallback)
TenantIdentity = namedtuple("TenantIdentity", "name router_enforced")


def resolve_reload_s() -> float:
    """``SONATA_TENANTS_RELOAD_S`` (the one default-defining read): the
    minimum seconds between hot-reload checks of the tenant table."""
    raw = os.environ.get(RELOAD_S_ENV, "").strip()
    if not raw:
        return DEFAULT_RELOAD_S
    try:
        return max(0.0, float(raw))
    except ValueError:
        log.warning("ignoring non-numeric %s=%r", RELOAD_S_ENV, raw)
        return DEFAULT_RELOAD_S


class TenantConfig:
    """One tenant's policy row (parsed, validated, clamped)."""

    __slots__ = ("name", "weight", "qps", "burst", "cache_share",
                 "shed_priority")

    def __init__(self, name: str, *, weight: float = 1.0,
                 qps: float = 0.0, burst: Optional[float] = None,
                 cache_share: float = 0.0, shed_priority: int = 0):
        self.name = str(name)
        self.weight = max(0.1, float(weight))
        self.qps = max(0.0, float(qps))
        #: bucket capacity; defaults to one second of refill (>= 1) so
        #: "qps: 2" alone means what an operator expects
        self.burst = (max(1.0, self.qps) if burst is None
                      else max(1.0, float(burst)))
        self.cache_share = min(1.0, max(0.0, float(cache_share)))
        self.shed_priority = int(shed_priority)

    def to_dict(self) -> dict:
        return {"weight": self.weight, "qps": self.qps,
                "burst": self.burst, "cache_share": self.cache_share,
                "shed_priority": self.shed_priority}

    def policy_key(self) -> tuple:
        return (self.weight, self.qps, self.burst, self.cache_share,
                self.shed_priority)


def parse_tenants(doc: dict) -> Dict[str, TenantConfig]:
    """``{"tenants": {name: {...}}}`` (or a bare name→row mapping) →
    validated config table.  The ``default`` tenant always exists —
    synthesized unlimited/weight-1 when not configured — because
    unlabeled and unknown-tenant traffic must always have a home."""
    rows = doc.get("tenants", doc) if isinstance(doc, dict) else None
    if not isinstance(rows, dict):
        raise ValueError("tenant config must be a JSON object "
                         '({"tenants": {name: {...}}})')
    table: Dict[str, TenantConfig] = {}
    for name, row in rows.items():
        if name in ("tenants", "revision") and not isinstance(row, dict):
            continue
        if not isinstance(row, dict):
            raise ValueError(f"tenant {name!r}: config row must be an "
                             "object")
        known = {"weight", "qps", "burst", "cache_share",
                 "shed_priority"}
        bad = sorted(set(row) - known)
        if bad:
            raise ValueError(f"tenant {name!r}: unknown field(s) "
                             f"{', '.join(bad)}")
        table[str(name)] = TenantConfig(str(name), **row)
    if DEFAULT_TENANT not in table:
        table[DEFAULT_TENANT] = TenantConfig(DEFAULT_TENANT)
    return table


def tenant_from_metadata(metadata) -> Optional[str]:
    """The raw ``x-tenant-id`` value from invocation metadata, or None
    (mirrors ``tracing.request_id_from_metadata``)."""
    for key, value in metadata or ():
        if str(key).lower() == TENANT_HEADER:
            return str(value)
    return None


def _metadata_value(metadata, header: str) -> Optional[str]:
    for key, value in metadata or ():
        if str(key).lower() == header:
            return str(value)
    return None


class TokenBucket:
    """One tenant's quota bucket: ``qps`` tokens/s refill into a
    ``burst``-deep bucket.  Deterministic under an injected clock (the
    test seam); a zero-qps bucket is unlimited."""

    __slots__ = ("qps", "burst", "_tokens", "_last", "_clock", "_lock")

    def __init__(self, qps: float, burst: float, clock=None):
        self.qps = float(qps)
        self.burst = float(burst)
        self._tokens = self.burst
        self._clock = clock if clock is not None else time.monotonic
        self._last = self._clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0):
        """Charge ``n`` tokens.  Returns ``(True, 0.0)`` on success or
        ``(False, retry_after_s)`` — the seconds until the deficit
        refills, the honest number a client should back off by."""
        if self.qps <= 0:
            return True, 0.0
        with self._lock:
            now = self._clock()
            elapsed = max(0.0, now - self._last)
            self._last = now
            self._tokens = min(self.burst, self._tokens + elapsed * self.qps)
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.qps

    def empty(self) -> bool:
        """True when a charge would be refused right now (the shed
        rung's over-quota signal) — read-only, no token movement."""
        if self.qps <= 0:
            return False
        with self._lock:
            now = self._clock()
            tokens = min(self.burst,
                         self._tokens + max(0.0, now - self._last) * self.qps)
            return tokens < 1.0

    def view(self) -> dict:
        with self._lock:
            return {"qps": self.qps, "burst": self.burst,
                    "tokens": round(self._tokens, 3)}


class _Waiter:
    __slots__ = ("event", "granted")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False


class FairGate:
    """Deficit-round-robin stream admission over per-tenant FIFOs.

    ``slots`` concurrent synthesis streams run; below saturation entry
    is immediate (and costs one lock acquisition).  At saturation each
    arriving stream parks in its tenant's own FIFO; every released slot
    is re-dealt by DRR — each pick adds ``weight/max_weight`` to the
    tenant's deficit and a full deficit buys one grant — so admitted
    work converges to weight proportion (2:1 weights → ~2:1 grants,
    pinned by tests/test_tenancy.py) and one tenant's burst can only
    deepen that tenant's queue.  A tenant whose queue drains loses its
    deficit (standard DRR: no banking idle credit).

    Total queued work is bounded upstream by the admission controller's
    capacity, so the per-tenant FIFOs need no cap of their own.
    """

    def __init__(self, weight_of: Callable[[str], float], slots: int):
        self.slots = max(1, int(slots))
        self._weight_of = weight_of
        self._lock = threading.Lock()
        self._active = 0
        #: tenant -> FIFO of parked waiters (insertion order = the DRR
        #: ring's rotation order for newly-active tenants)
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._deficit: Dict[str, float] = {}
        self._running: Dict[str, int] = {}
        self._grants: Dict[str, int] = {}
        self._rr: deque = deque()  # tenant rotation ring

    # -- entry/exit ----------------------------------------------------------
    def enter(self, tenant: str, timeout_s: Optional[float] = None) -> bool:
        """Take one synthesis slot for ``tenant`` (blocking fairly when
        saturated).  False = the wait timed out — the stream never ran,
        do not call :meth:`leave`."""
        with self._lock:
            if self._active < self.slots and not self._any_queued_locked():
                self._grant_locked(tenant)
                return True
            waiter = _Waiter()
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._rr.append(tenant)
            q.append(waiter)
        if waiter.event.wait(timeout_s):
            return True
        with self._lock:
            if waiter.granted:
                # the grant raced the timeout: the slot is ours after all
                return True
            try:
                self._queues[tenant].remove(waiter)
            except (KeyError, ValueError):
                pass
            return False

    def leave(self, tenant: str) -> None:
        """Release the slot taken by :meth:`enter` and deal freed slots
        to parked waiters by DRR."""
        with self._lock:
            self._active = max(0, self._active - 1)
            n = self._running.get(tenant, 0)
            if n <= 1:
                self._running.pop(tenant, None)
            else:
                self._running[tenant] = n - 1
            self._deal_locked()

    # -- DRR core (all under self._lock) -------------------------------------
    def _grant_locked(self, tenant: str) -> None:
        self._active += 1
        self._running[tenant] = self._running.get(tenant, 0) + 1
        self._grants[tenant] = self._grants.get(tenant, 0) + 1

    def _any_queued_locked(self) -> bool:
        return any(self._queues.values())

    def _deal_locked(self) -> None:
        while self._active < self.slots:
            waiter, tenant = self._pick_locked()
            if waiter is None:
                break
            self._grant_locked(tenant)
            waiter.granted = True
            waiter.event.set()

    def _pick_locked(self):
        busy = [t for t, q in self._queues.items() if q]
        if not busy:
            # nobody parked: reset deficits so idle tenants bank nothing
            self._deficit.clear()
            return None, None
        wmax = max(self._weight_of(t) for t in busy) or 1.0
        # each ring pass adds >= 0.1/wmax to someone's deficit, so the
        # guard is generous slack, not a correctness bound
        for _ in range(64 * len(self._rr) + 64):
            if not self._rr:
                return None, None
            tenant = self._rr[0]
            self._rr.rotate(-1)
            q = self._queues.get(tenant)
            if q is None or not q:
                self._deficit.pop(tenant, None)
                continue
            credit = self._deficit.get(tenant, 0.0) + (
                self._weight_of(tenant) / wmax)
            if credit >= 1.0:
                self._deficit[tenant] = credit - 1.0
                return q.popleft(), tenant
            self._deficit[tenant] = credit
        return None, None

    # -- observability --------------------------------------------------------
    def queue_depth(self, tenant: str) -> int:
        with self._lock:
            q = self._queues.get(tenant)
            return len(q) if q else 0

    def grants(self, tenant: str) -> int:
        with self._lock:
            return self._grants.get(tenant, 0)

    def active_mix(self) -> Dict[str, int]:
        """tenant → running synthesis streams (the padding-waste
        chargeback pro-ration the scope plane consumes)."""
        with self._lock:
            return dict(self._running)

    def view(self) -> dict:
        with self._lock:
            return {"slots": self.slots, "active": self._active,
                    "queued": {t: len(q) for t, q in self._queues.items()
                               if q},
                    "running": dict(self._running)}


class TenantPlane:
    """The per-process tenant control plane: config table + hot reload,
    classification, token buckets, the fair gate (node processes), the
    shed rung, per-tenant counters, and the desired-state apply surface
    the mesh router pushes to.  Built by :func:`from_env`; absent
    (None) when ``SONATA_TENANTS`` is unset — every hook then costs one
    ``is None`` branch and the request path is byte-for-byte pre-PR."""

    def __init__(self, source: str, *, fair_slots: Optional[int] = None,
                 clock=None):
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.RLock()
        self._source = source
        #: None source: an empty table (default tenant only) with no
        #: file/env reloads — the push-only shape tests construct
        self._source_is_path = (source is not None
                                and not source.lstrip().startswith("{"))
        self._reload_s = resolve_reload_s()
        self._last_reload_check = self._clock()
        self._mtime = self._stat_source()
        self.revision = 1
        #: >0 once the mesh router pushed a table: the router is then
        #: authoritative and local file reloads stop (desired state)
        self.remote_revision = 0
        self._tenants = self._parse_source(source)
        self._buckets: Dict[str, TokenBucket] = {}
        self._stats: Dict[str, Dict[str, int]] = {}
        self._classify_errors = 0
        self.fair = (FairGate(self.weight_of, fair_slots)
                     if fair_slots is not None else None)
        # metrics plumbing (bind_metrics / lazy per-tenant series)
        self._registry = None
        self._families: Dict[str, object] = {}
        self._series: Dict[str, list] = {}

    # -- config source --------------------------------------------------------
    def _stat_source(self):
        if not self._source_is_path:
            return None
        try:
            st = os.stat(self._source)
            return (st.st_mtime, st.st_size)
        except OSError:
            return None

    def _parse_source(self, source) -> Dict[str, TenantConfig]:
        if source is None:
            return parse_tenants({})
        if source.lstrip().startswith("{"):
            return parse_tenants(json.loads(source))
        with open(source, "r", encoding="utf-8") as f:
            return parse_tenants(json.load(f))

    def maybe_reload(self) -> bool:
        """Hot-reload check, rate-limited to ``SONATA_TENANTS_RELOAD_S``
        and disabled once a router push took ownership.  A parse error
        keeps the old table (a fat-fingered edit must not drop quota
        enforcement mid-incident).  Returns True when a new table
        swapped in."""
        with self._lock:
            if self.remote_revision > 0:
                return False
            now = self._clock()
            if now - self._last_reload_check < self._reload_s:
                return False
            self._last_reload_check = now
            if self._source_is_path:
                mtime = self._stat_source()
                if mtime is None or mtime == self._mtime:
                    return False
                self._mtime = mtime
                source = self._source
            else:
                source = os.environ.get(TENANTS_ENV, "").strip()
                if not source or source == self._source:
                    return False
                self._source = source
        # parse outside the lock (file I/O must not stall classify/
        # charge on the request path); concurrent reloaders both parse,
        # the swap below is last-writer-wins on the same source
        try:
            table = self._parse_source(source)
        except (OSError, ValueError) as e:
            log.warning("tenant-table reload failed (%s); keeping "
                        "revision %d", e, self.revision)
            return False
        with self._lock:
            if self.remote_revision > 0:
                return False  # a router push raced the parse: it wins
            self._swap_locked(table)
            log.info("tenant table hot-reloaded: revision %d, %d "
                     "tenant(s)", self.revision, len(self._tenants))
            return True

    def apply_remote(self, doc: dict) -> bool:
        """Desired-state apply from the mesh router (``POST
        /debug/tenants``): ``{"revision": N, "tenants": {...}}``.
        Applies only when ``N`` advances past the last applied remote
        revision — re-pushes are idempotent, stale pushes are refused —
        and takes ownership from local reloads."""
        revision = doc.get("revision")
        if not isinstance(revision, int) or revision <= 0:
            raise ValueError("remote tenant config needs a positive "
                             "integer revision")
        table = parse_tenants(doc)
        with self._lock:
            if revision <= self.remote_revision:
                return False
            self.remote_revision = revision
            self._swap_locked(table)
            log.info("tenant table applied from router: remote revision "
                     "%d, %d tenant(s)", revision, len(self._tenants))
            return True

    def _swap_locked(self, table: Dict[str, TenantConfig]) -> None:
        """Swap the config table; buckets whose policy is unchanged keep
        their fill (a reload must not hand every tenant a fresh burst)."""
        for name in list(self._buckets):
            old = self._tenants.get(name)
            new = table.get(name)
            if (old is None or new is None
                    or old.policy_key() != new.policy_key()):
                del self._buckets[name]
        self._tenants = table
        self.revision += 1

    def config_doc(self) -> dict:
        """The propagation payload (router side): the current table
        under this plane's revision."""
        with self._lock:
            return {"revision": self.revision,
                    "tenants": {n: c.to_dict()
                                for n, c in self._tenants.items()}}

    # -- identity -------------------------------------------------------------
    def classify(self, metadata) -> TenantIdentity:
        """Resolve one request's tenant from invocation metadata.

        The ``tenancy.classify`` failpoint wraps the extraction: an
        injected (or real) error degrades to the ``default`` tenant —
        the request is SERVED and counted, never refused on a
        classification failure.  Unknown tenant ids land in ``default``
        too (bounded label cardinality)."""
        try:
            faults.fire("tenancy.classify")
            routed = _metadata_value(metadata, ROUTER_TENANT_HEADER)
            enforced = (_metadata_value(metadata, ROUTER_ENFORCED_HEADER)
                        == ROUTER_ENFORCED_VALUE)
            name = routed if routed is not None else tenant_from_metadata(
                metadata)
            with self._lock:
                if name not in self._tenants:
                    name = DEFAULT_TENANT
            # the router's enforcement marker only counts when it names
            # a tenant this node also knows — a stale marker falls back
            # to local charging, never to a free pass for unknown ids
            return TenantIdentity(name, enforced and routed == name)
        except Exception:
            with self._lock:
                self._classify_errors += 1
            log.debug("tenant classification degraded to %r",
                      DEFAULT_TENANT, exc_info=True)
            return TenantIdentity(DEFAULT_TENANT, False)

    def classify_context(self, context) -> TenantIdentity:
        """:meth:`classify` from a gRPC ServicerContext (the metadata
        fetch rides inside the failpoint's degrade-to-default)."""
        try:
            metadata = context.invocation_metadata()
        except Exception:
            metadata = None
        return self.classify(metadata)

    # -- quota ----------------------------------------------------------------
    def _cfg(self, name: str) -> TenantConfig:
        with self._lock:
            cfg = self._tenants.get(name)
            return cfg if cfg is not None else self._tenants[DEFAULT_TENANT]

    def _bucket(self, name: str) -> Optional[TokenBucket]:
        cfg = self._cfg(name)
        if cfg.qps <= 0:
            return None
        with self._lock:
            bucket = self._buckets.get(name)
            if bucket is None:
                bucket = self._buckets[name] = TokenBucket(
                    cfg.qps, cfg.burst, clock=self._clock)
            return bucket

    def charge(self, identity: TenantIdentity):
        """Token-bucket charge for one SYNTHESIS (cache hits never get
        here).  Returns ``(True, 0.0)`` or ``(False, retry_after_s)``;
        a refusal is counted.  When the mesh router already enforced
        quota for this hop the node charge is skipped — router wins,
        per-node buckets are the fallback."""
        self.maybe_reload()
        if identity.router_enforced:
            return True, 0.0
        bucket = self._bucket(identity.name)
        if bucket is None:
            return True, 0.0
        ok, retry_after = bucket.try_take()
        if not ok:
            self._bump(identity.name, "quota_rejections")
        return ok, retry_after

    # -- shed rung ------------------------------------------------------------
    def shed_rung(self, name: str, level: int) -> bool:
        """The per-tenant rung on the degradation ladder: True when this
        tenant's request should shed BEFORE any fleet-wide rung.  At
        level >= 1 background tenants (``shed_priority`` > 0) shed; at
        level >= 2 any tenant currently over quota (empty bucket) sheds
        too.  The caller counts via :meth:`note_shed` and raises the
        same typed ``Overloaded`` the fleet-wide rung uses."""
        if level < 1:
            return False
        cfg = self._cfg(name)
        if cfg.shed_priority > 0:
            return True
        if level >= 2:
            bucket = self._bucket(name)
            if bucket is not None and bucket.empty():
                return True
        return False

    # -- accounting -----------------------------------------------------------
    def _bump(self, name: str, stat: str) -> None:
        with self._lock:
            stats = self._stats.get(name)
            if stats is None:
                stats = self._stats[name] = {
                    "admitted": 0, "quota_rejections": 0, "shed": 0}
            stats[stat] += 1
        self._ensure_tenant_series(name)

    def note_admitted(self, name: str) -> None:
        self._bump(name, "admitted")

    def note_shed(self, name: str) -> None:
        self._bump(name, "shed")

    def stat(self, name: str, stat: str) -> float:
        with self._lock:
            stats = self._stats.get(name)
            return float(stats[stat]) if stats else 0.0

    @property
    def classify_errors(self) -> int:
        with self._lock:
            return self._classify_errors

    def weight_of(self, name: str) -> float:
        return self._cfg(name).weight

    def cache_share(self, name: Optional[str]) -> Optional[float]:
        """The tenant's fraction of the synthesis-cache byte budget, or
        None (unshared) — the ``SynthCache`` owner-budget resolver."""
        if name is None:
            return None
        share = self._cfg(name).cache_share
        return share if share > 0 else None

    def active_mix(self) -> Dict[str, int]:
        return self.fair.active_mix() if self.fair is not None else {}

    def tenant_names(self) -> list:
        with self._lock:
            return sorted(self._tenants)

    def snapshot(self) -> dict:
        """``GET /debug/tenants``: config + counters + queue state."""
        with self._lock:
            # copy refs under the lock, render views outside it — the
            # bucket/fair views take their own locks and must never
            # nest under the plane lock
            configs = dict(self._tenants)
            stats = {n: dict(s) for n, s in self._stats.items()}
            buckets = dict(self._buckets)
            doc = {"revision": self.revision,
                   "remote_revision": self.remote_revision,
                   "source": ("inline" if not self._source_is_path
                              else self._source),
                   "classify_errors": self._classify_errors}
        doc["tenants"] = {
            name: {**cfg.to_dict(),
                   "counters": stats.get(name, {}),
                   "bucket": (buckets[name].view()
                              if name in buckets else None)}
            for name, cfg in configs.items()}
        if self.fair is not None:
            doc["fair"] = self.fair.view()
            for name, row in doc["tenants"].items():
                row["queue_depth"] = self.fair.queue_depth(name)
        return doc

    # -- metrics --------------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Register the tenant families (table-driven) plus the
        classification-degrade counter.  Per-tenant labeled series are
        created lazily on first activity and removed exactly by
        :meth:`unregister_tenant_series` (the fleetscope idiom: a
        labeled series outliving its plane would scrape stale)."""
        self._registry = registry
        for name, help in TENANT_COUNTER_FAMILIES:
            self._families[name] = registry.counter(name, help)
        for name, help in TENANT_GAUGE_FAMILIES:
            self._families[name] = registry.gauge(name, help)
        registry.counter(
            "sonata_tenancy_classify_errors_total",
            "Requests whose tenant classification failed (the "
            "tenancy.classify failpoint or a real extraction error) and "
            "degraded to the default tenant — served, never refused."
        ).set_function(lambda: float(self.classify_errors))
        # configured tenants get their series up front (a dashboard
        # should see zero rows before traffic); unknown-id traffic all
        # lands in `default`, so lazy creation only ever adds tenants a
        # reload introduced
        for name in self.tenant_names():
            self._ensure_tenant_series(name)

    def _ensure_tenant_series(self, tenant: str) -> None:
        if self._registry is None:
            return
        with self._lock:
            if tenant in self._series:
                return
            owned = self._series[tenant] = []
        stats = (("sonata_tenant_admitted_total", "admitted"),
                 ("sonata_tenant_quota_rejections_total",
                  "quota_rejections"),
                 ("sonata_tenant_shed_total", "shed"))
        for family, stat in stats:
            metric = self._families[family]
            labels = {"tenant": tenant}
            metric.labels(**labels).set_function(
                lambda t=tenant, s=stat: self.stat(t, s))
            owned.append((metric, labels))
        depth = self._families["sonata_tenant_queue_depth"]
        labels = {"tenant": tenant}
        depth.labels(**labels).set_function(
            lambda t=tenant: float(self.fair.queue_depth(t))
            if self.fair is not None else 0.0)
        owned.append((depth, labels))

    def unregister_tenant_series(self) -> None:
        with self._lock:
            series, self._series = self._series, {}
        for owned in series.values():
            for metric, labels in owned:
                try:
                    metric.remove(**labels)
                except Exception:
                    pass

    def close(self) -> None:
        self.unregister_tenant_series()


def from_env(*, fair_slots: Optional[int] = None,
             clock=None) -> Optional[TenantPlane]:
    """The runtime's construction gate: a :class:`TenantPlane` when
    ``SONATA_TENANTS`` is set and parses, else None (the default — the
    pre-tenancy request path is then byte-for-byte unchanged, pinned).
    A present-but-broken config logs loudly and stays OFF: a typo must
    not boot a server with surprise quotas."""
    raw = os.environ.get(TENANTS_ENV, "").strip()
    if not raw:
        return None
    try:
        return TenantPlane(raw, fair_slots=fair_slots, clock=clock)
    except (OSError, ValueError) as e:
        log.error("%s=%r did not parse (%s); tenancy stays OFF",
                  TENANTS_ENV, raw, e)
        return None


class ConfigPropagator:
    """Router-side desired-state push of the tenant table to node
    planes (the placement registry's pattern, riding the mesh prober
    threads): each node is POSTed ``/debug/tenants`` whenever its last
    acked revision trails the router's table, on its own cadence, and a
    restarted node (acks reset with its process) converges on the next
    cycle with zero operator action.  A node with tenancy disabled
    answers 404 and is left alone — enabling tenancy is the node
    operator's call, the router only synchronizes tables."""

    def __init__(self, plane: TenantPlane, *, interval_s: float = 5.0,
                 post=None, clock=None):
        from .placement import ProbeCadence

        self.plane = plane
        self._cadence = ProbeCadence(interval_s, clock=clock)
        self._post = post if post is not None else _http_post_json
        self._lock = threading.Lock()
        #: node index -> last revision that node acked
        self._acked: Dict[int, int] = {}
        #: node index -> due cycles skipped since the last push; at
        #: REFRESH_CYCLES the push repeats even when acked — the
        #: anti-entropy floor that re-converges a restarted node (its
        #: process lost the table, the router-side ack did not)
        self._skips: Dict[int, int] = {}
        self.pushes = 0
        self.push_errors = 0

    #: due cycles between forced re-pushes to an acked node (at the
    #: default 5 s cadence: a restarted node is stale for ~2 min worst
    #: case, same order as the placement reconciler's anti-entropy)
    REFRESH_CYCLES = 24

    def on_probe_cycle(self, node) -> None:
        """Mesh prober hook (the attach pattern): converge ``node``'s
        tenant table if due and trailing."""
        if not self._cadence.due(node.index):
            return
        base = node.spec.metrics_base
        if base is None:
            return
        doc = self.plane.config_doc()
        with self._lock:
            if self._acked.get(node.index) == doc["revision"]:
                skips = self._skips.get(node.index, 0) + 1
                if skips < self.REFRESH_CYCLES:
                    self._skips[node.index] = skips
                    return
            self._skips[node.index] = 0
        try:
            reply = self._post(base + "/debug/tenants", doc)
        except Exception as e:
            with self._lock:
                self.push_errors += 1
            log.debug("tenant-config push to node %s failed: %s",
                      node.spec.node_id, e)
            return
        with self._lock:
            self.pushes += 1
            if isinstance(reply, dict) and reply.get("revision"):
                self._acked[node.index] = doc["revision"]

    def forget(self, node) -> None:
        """A node left (or restarted under the same index): drop its
        ack so the next cycle re-pushes."""
        with self._lock:
            self._acked.pop(node.index, None)
            self._skips.pop(node.index, None)

    def view(self) -> dict:
        with self._lock:
            return {"revision": self.plane.revision,
                    "acked": dict(self._acked), "pushes": self.pushes,
                    "push_errors": self.push_errors}


def _http_post_json(url: str, doc: dict, timeout_s: float = 2.0) -> dict:
    """POST one JSON document, JSON reply (the propagation transport —
    same urllib plane the fleet scrape uses, injectable in tests)."""
    import urllib.request

    body = json.dumps(doc).encode("utf-8")
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))
