"""sonata-mesh core: federate sonata servers into one serving fleet.

:class:`~sonata_tpu.serving.replicas.ReplicaPool` stops at the local
chips of one process — the r08 bench hit that wall directly (two
replicas contending on 2 vCPUs; the scale-out axis is more *hosts*, not
more threads).  This module is the routing tier above it: N backend
sonata servers (each its own process with its own pool, drain, warmup,
and iteration loop) federated behind one endpoint, where a dead,
draining, wedged, or partitioned node is a **routing event**, not a
user-visible error.  The transport-agnostic core lives here; the gRPC
frontend that drives it is :mod:`sonata_tpu.frontends.mesh_server`.

Pieces, in dependency order:

- **Health-gated membership.**  One prober thread per node (a wedged
  health endpoint must not stall the probes of its peers) scrapes
  ``/readyz`` plus ``/metrics`` every ``SONATA_MESH_PROBE_INTERVAL_S``:
  ``sonata_draining`` evicts a draining node from membership *before*
  its listener stops, ``sonata_replica_outstanding`` (fallback
  ``sonata_in_flight``) feeds the routing tiebreak, and
  ``sonata_node_info`` teaches the router the backend's stable
  ``node_id`` so router-side logs and spans name the process that
  served each request.  A 503 ``/readyz`` (warming, degraded) makes the
  node unroutable but is **not** a fault; an unreachable plane is.
- **Per-node circuit breaker**, the PR-5/6 replica state machine at
  node granularity: ``SONATA_MESH_BREAKER_THRESHOLD`` consecutive
  failures trip the node OPEN.  Probe failures and route-class request
  failures keep **separate** consecutive counters (a node answering its
  health endpoint while erroring every request must still trip — a
  shared counter would let each probe success launder the route
  failures accumulated between scrapes); once the
  backed-off ``next_probe_at`` passes, a successful probe of a ready
  node flips it HALF_OPEN and the next routed request is its trial —
  success closes the breaker, failure re-opens with the probe backoff
  doubled (jittered, capped at ``SONATA_MESH_PROBE_MAX_S``).  A
  recovered backend therefore **rejoins membership with no router
  restart**.
- **Least-outstanding routing with an iteration-headroom tiebreak**:
  primary key is the router's own live in-flight count per node; ties
  break toward the node with the most slots left below its current
  graduated batch rung (:data:`~sonata_tpu.utils.buckets.BATCH_BUCKETS`
  over router + scraped occupancy) — a new stream should fill a rung,
  not graduate one (the PR-10/11 padding economics, fleet edition).
- **Deadline and admission propagation over the hop**: the remaining
  deadline at each attempt — shrunk by queue wait, failed attempts, and
  backoff sleeps — becomes the per-attempt transport timeout.
- **Bounded retry** (:meth:`MeshRouter.route_stream`): route-class
  failures (connect errors, injected ``mesh.route`` faults, typed
  UNAVAILABLE) retry another node with exponential backoff + jitter;
  a typed ``draining`` refusal reroutes *immediately* (a deploy is not
  a fault: no breaker count, no backoff) and marks the node draining
  at once rather than waiting for the next scrape.  **Never after
  bytes reached the client**: once the first chunk has been yielded,
  any failure is typed through — resending audio is worse than failing.
- **First-chunk hedge** (``SONATA_MESH_HEDGE_MS``, default 0 = off):
  when armed, an attempt that produced no first chunk inside the budget
  is cancelled and rerouted (counts against the same retry budget;
  never duplicates audio because it only ever fires pre-first-chunk).

Failpoint sites: ``mesh.route`` fires inside every per-node dispatch
attempt (an injected error counts toward that node's breaker exactly
like a real one), ``mesh.health`` fires inside every probe cycle, and
``mesh.reconcile`` fires inside every voice-placement reconcile cycle —
so the chaos lane can kill, wedge, or partition a node deterministically
without owning real processes.

The fleet observability plane (ISSUE 13) rides the same per-node
prober: an attached :class:`~sonata_tpu.serving.fleetscope.FleetScope`
gets :meth:`FleetScope.on_probe_cycle` after every health cycle and
pulls the node's ``/debug/scope/export`` on its own slower cadence.
The router only holds the bookkeeping: ``scope_scrape_at`` /
``scope_stale`` per node, with a stale scrape (the fleet scraper's
staleness budget exceeded) making the node **unroutable** — a node
whose observability plane is wedged must not keep looking healthy just
because the last good scrape said so.

The voice-placement plane (ISSUE 14,
:class:`~sonata_tpu.serving.placement.PlacementPlane`) rides the same
probers: each health cycle scrapes the node's *actual* loaded-voice
set (the ``voices=`` line on ``/readyz``, falling back to the
``sonata_voice_loaded`` gauge) and drives one reconcile cycle per
``SONATA_PLACEMENT_RECONCILE_INTERVAL_S``; the router holds the
per-node actual set, the per-(node, voice) outstanding counts, and the
voice-aware restriction in :meth:`MeshRouter.pick`.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Iterator, List, Optional, Sequence

from ..core import OperationError
from ..utils.buckets import BATCH_BUCKETS
from . import faults, tracing
from .admission import Overloaded
from .deadlines import Deadline
from .drain import Draining
from .metrics import parse_prometheus_text
from .placement import VoiceWarming
from .replicas import CLOSED, HALF_OPEN, OPEN, _STATE_NAMES, _env_float, _env_int

log = logging.getLogger("sonata.serving")

MESH_BACKENDS_ENV = "SONATA_MESH_BACKENDS"
NODE_ID_ENV = "SONATA_NODE_ID"
MESH_PROBE_INTERVAL_ENV = "SONATA_MESH_PROBE_INTERVAL_S"
MESH_PROBE_TIMEOUT_ENV = "SONATA_MESH_PROBE_TIMEOUT_S"
MESH_BREAKER_THRESHOLD_ENV = "SONATA_MESH_BREAKER_THRESHOLD"
MESH_PROBE_MAX_ENV = "SONATA_MESH_PROBE_MAX_S"
MESH_RETRIES_ENV = "SONATA_MESH_RETRIES"
MESH_RETRY_BACKOFF_ENV = "SONATA_MESH_RETRY_BACKOFF_MS"
MESH_HEDGE_ENV = "SONATA_MESH_HEDGE_MS"

DEFAULT_PROBE_INTERVAL_S = 0.5
DEFAULT_PROBE_TIMEOUT_S = 2.0
DEFAULT_BREAKER_THRESHOLD = 3
DEFAULT_PROBE_MAX_S = 30.0
DEFAULT_RETRIES = 2
DEFAULT_RETRY_BACKOFF_MS = 50.0
#: reroute backoff is capped well below any request deadline: the retry
#: exists to dodge a sick node, not to wait one back to health
MAX_RETRY_BACKOFF_S = 2.0
#: fractional jitter on retry backoff and probe rescheduling, so a fleet
#: of routers tripped by one node death does not re-probe in lockstep
MESH_JITTER = 0.1

#: sentinel for "the backend stream ended before a first chunk" (an
#: empty stream is a legitimate completion, not a failure)
_DONE = object()


class _HedgeCancelled(Exception):
    """The first-chunk hedge cancelled an attempt racing its own first
    chunk — rerouted like any hedge fire (nothing reached the client)."""


#: the metric families the membership prober actually reads (scrape
#: lines are pre-filtered to these before parsing)
_SCRAPE_FAMILIES = ("sonata_draining", "sonata_replica_outstanding",
                    "sonata_in_flight", "sonata_node_info",
                    "sonata_voice_loaded")


def resolve_node_id(default: str) -> str:
    """Stable node identity: ``SONATA_NODE_ID`` wins, else the bind
    ``host:port``.  This is the name router-side logs, spans, and
    clients (via gRPC trailing metadata) know the backend by."""
    raw = os.environ.get(NODE_ID_ENV, "").strip()
    return raw or default


class NodeSpec:
    """One backend's addresses: ``host:grpc_port[/metrics_port]``.

    The metrics port is where the node's ``/readyz`` + ``/metrics``
    plane lives; without one, membership is driven by route outcomes
    only: a tripped breaker still recovers (probe cycles count as
    optimistic successes, so OPEN walks to HALF_OPEN and a trial
    request closes it), but there is no scrape-driven drain eviction,
    no occupancy tiebreak, and a node evicted by a typed draining
    refusal stays evicted until a router restart.
    """

    __slots__ = ("host", "grpc_port", "metrics_port")

    def __init__(self, host: str, grpc_port: int,
                 metrics_port: Optional[int] = None):
        self.host = host
        self.grpc_port = int(grpc_port)
        self.metrics_port = int(metrics_port) if metrics_port else None

    @classmethod
    def parse(cls, spec: str) -> "NodeSpec":
        text = spec.strip()
        metrics: Optional[str] = None
        if "/" in text:
            text, _, metrics = text.partition("/")
        host, sep, port = text.rpartition(":")
        if not sep or not host:
            raise OperationError(
                f"bad mesh backend spec {spec!r} "
                "(host:grpc_port[/metrics_port])")
        try:
            return cls(host, int(port), int(metrics) if metrics else None)
        except ValueError:
            raise OperationError(
                f"bad mesh backend spec {spec!r}: ports must be "
                "integers (host:grpc_port[/metrics_port])") from None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.grpc_port}"

    @property
    def metrics_base(self) -> Optional[str]:
        if self.metrics_port is None:
            return None
        return f"http://{self.host}:{self.metrics_port}"

    def __repr__(self) -> str:
        return (f"NodeSpec({self.addr}"
                + (f"/{self.metrics_port}" if self.metrics_port else "")
                + ")")


def parse_backends(raw: Optional[str] = None) -> List[NodeSpec]:
    """Comma-separated backend specs; defaults to
    ``SONATA_MESH_BACKENDS``.  Duplicate addresses are rejected — two
    membership entries for one process would double-count its load."""
    if raw is None:
        raw = os.environ.get(MESH_BACKENDS_ENV, "")
    specs = [NodeSpec.parse(s) for s in raw.split(",") if s.strip()]
    seen: set = set()
    for s in specs:
        if s.addr in seen:
            raise OperationError(
                f"duplicate mesh backend {s.addr!r} in {raw!r}")
        seen.add(s.addr)
    return specs


class MeshNode:
    """One backend process's membership entry: identity + breaker +
    live/scraped load.  All mutation happens under the router's lock."""

    def __init__(self, index: int, spec: NodeSpec):
        self.index = index
        self.spec = spec
        #: stable identity; the spec address until a scrape of
        #: ``sonata_node_info`` teaches us the backend's own id
        self.node_id = spec.addr
        self.state = CLOSED
        #: optimistic until the first probe — a router with no metrics
        #: plane configured still routes, learning only from outcomes
        self.ready = True
        self.draining = False
        #: consecutive ROUTE-class request failures (reset by a route
        #: success); probes keep their own counter — see the module
        #: docstring on why they never launder each other
        self.consecutive_failures = 0
        self.consecutive_probe_failures = 0
        #: consecutive placement-reconcile failures — a THIRD separate
        #: counter, for the same reason probes and routes have their
        #: own: probes run every 0.5 s and reconciles every 2 s, so a
        #: shared counter would let each probe success launder the
        #: reconcile failures accumulated between cycles and a node
        #: whose control plane can never be reconciled would never trip
        self.consecutive_reconcile_failures = 0
        self.outstanding = 0            # router-side in-flight
        self.reported_outstanding = 0.0  # scraped backend occupancy
        self.routed = 0
        self.route_failures = 0
        self.probe_failures = 0
        self.last_probe_at: Optional[float] = None
        self.opened_at: Optional[float] = None
        self.next_probe_at: Optional[float] = None
        self.probe_backoff_s: Optional[float] = None
        #: fleet observability bookkeeping (ISSUE 13): monotonic stamp
        #: of the last good ``/debug/scope/export`` scrape, and the
        #: staleness verdict the attached FleetScope maintains — stale
        #: means unroutable (see the module docstring)
        self.scope_scrape_at: Optional[float] = None
        self.scope_stale = False
        #: voice placement (ISSUE 14): the node's ACTUAL loaded-voice
        #: set, scraped from the ``voices=`` line on ``/readyz`` (or
        #: the ``sonata_voice_loaded`` gauge); None until a scrape has
        #: reported one — an unknown actual set keeps PR-12 semantics
        #: (no reconcile ops, permissive voice-aware routing)
        self.loaded_voices: Optional[frozenset] = None
        #: router-side in-flight per voice on this node (what the RAM
        #: budget's never-evict-a-live-voice guard reads)
        self.voice_outstanding: dict = {}

    def snapshot(self) -> dict:
        return {"node_id": self.node_id, "addr": self.spec.addr,
                "index": self.index,
                "state": _STATE_NAMES[self.state],
                "ready": self.ready, "draining": self.draining,
                "outstanding": self.outstanding,
                "reported_outstanding": self.reported_outstanding,
                "routed": self.routed,
                "route_failures": self.route_failures,
                "probe_failures": self.probe_failures,
                "consecutive_failures": self.consecutive_failures,
                "consecutive_probe_failures":
                    self.consecutive_probe_failures,
                "consecutive_reconcile_failures":
                    self.consecutive_reconcile_failures,
                "probe_backoff_s": self.probe_backoff_s,
                "scope_stale": self.scope_stale,
                "voices": (None if self.loaded_voices is None
                           else sorted(self.loaded_voices)),
                "scope_scrape_age_s": (
                    None if self.scope_scrape_at is None
                    else round(time.monotonic() - self.scope_scrape_at,
                               3))}


def default_classify(exc: BaseException) -> str:
    """Failure class for transports raising typed errors: ``draining``
    (reroute immediately, no breaker count), ``route`` (reroute with
    backoff, counts toward the node breaker), or ``fatal`` (typed
    through).  gRPC frontends supply their own status-code-aware
    classifier."""
    if isinstance(exc, Draining):
        return "draining"
    if isinstance(exc, faults.InjectedFault):
        return "route"
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return "route"
    return "fatal"


def _http_fetch(url: str, timeout_s: float) -> tuple:
    """(status code, body text); HTTP error codes are answers, not
    exceptions — only an unreachable plane raises."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.getcode(), resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8", "replace")


class MeshRouter:
    """Membership + breaker + routing over :class:`MeshNode` entries.

    Transport-agnostic: :meth:`route_stream` drives a caller-supplied
    ``start(node, timeout_s)`` callable, so the gRPC frontend and the
    fake-backend unit tests share every line of the retry/breaker/
    membership logic.
    """

    def __init__(self, specs: Sequence[NodeSpec], *,
                 probe_interval_s: Optional[float] = None,
                 probe_timeout_s: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 probe_max_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 retry_backoff_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 name: str = "mesh",
                 fetch: Optional[Callable[[str, float], tuple]] = None,
                 start_probers: bool = True):
        if not specs:
            raise OperationError(
                "a mesh needs at least one backend "
                f"(set {MESH_BACKENDS_ENV} or pass --backend)")
        self.name = name
        self.probe_interval_s = max(0.05, (
            probe_interval_s if probe_interval_s is not None
            else _env_float(MESH_PROBE_INTERVAL_ENV, DEFAULT_PROBE_INTERVAL_S)))
        self.probe_timeout_s = max(0.05, (
            probe_timeout_s if probe_timeout_s is not None
            else _env_float(MESH_PROBE_TIMEOUT_ENV, DEFAULT_PROBE_TIMEOUT_S)))
        self.breaker_threshold = max(1, (
            breaker_threshold if breaker_threshold is not None
            else _env_int(MESH_BREAKER_THRESHOLD_ENV,
                          DEFAULT_BREAKER_THRESHOLD)))
        # never below the probe interval (same contract as the pool cap)
        self.probe_max_s = max(self.probe_interval_s, (
            probe_max_s if probe_max_s is not None
            else _env_float(MESH_PROBE_MAX_ENV, DEFAULT_PROBE_MAX_S)))
        self.retries = max(0, (
            retries if retries is not None
            else _env_int(MESH_RETRIES_ENV, DEFAULT_RETRIES)))
        self.retry_backoff_ms = max(0.0, (
            retry_backoff_ms if retry_backoff_ms is not None
            else _env_float(MESH_RETRY_BACKOFF_ENV, DEFAULT_RETRY_BACKOFF_MS)))
        self.hedge_ms = max(0.0, (
            hedge_ms if hedge_ms is not None
            else _env_float(MESH_HEDGE_ENV, 0.0)))
        self._fetch = fetch if fetch is not None else _http_fetch
        self._lock = threading.RLock()
        self._closed = False
        self.nodes = [MeshNode(i, s) for i, s in enumerate(specs)]
        self.stats = {"routed": 0, "rerouted": 0, "rerouted_draining": 0,
                      "hedged": 0, "failed": 0, "breaker_opens": 0,
                      "recovered": 0, "probe_failures": 0}
        self._wake = threading.Event()
        #: attached fleet observability plane (ISSUE 13) — probed on
        #: every cycle, scrapes on its own cadence; None costs one read
        self._fleet = None
        #: attached voice-placement plane (ISSUE 14) — reconciles on
        #: the prober threads, restricts voice-aware routing
        self._placement = None
        #: attached fleet cache tier (ISSUE 16) — biases routing of
        #: cacheable requests toward their rendezvous owner, replicates
        #: hot entries on the prober threads; None costs one read
        self._fleetcache = None
        #: attached tenant-config propagator (ISSUE 17) — pushes the
        #: router's tenant table to nodes on the prober threads
        self._tenancy_propagator = None
        self._probers: list = []
        if start_probers:
            for node in self.nodes:
                t = threading.Thread(
                    target=self._probe_loop, args=(node,),
                    name=f"sonata_mesh_probe_{node.index}", daemon=True)
                t.start()
                self._probers.append(t)

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        """Stop membership probing (terminal).  In-flight routed streams
        are untouched — they finish or fail through their transport."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        for t in self._probers:
            t.join(timeout=self.probe_timeout_s + 5.0)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- fleet observability attachment (ISSUE 13) ----------------------------
    def attach_fleet(self, fleet) -> None:
        """Attach the fleet aggregation plane: each node's prober calls
        ``fleet.on_probe_cycle(node)`` after every health cycle (the
        scope-export scrape rides the prober thread on the fleet's own
        slower cadence, so a wedged node export can never stall a
        peer's probes either)."""
        self._fleet = fleet

    # -- voice placement attachment (ISSUE 14) --------------------------------
    def attach_placement(self, plane) -> None:
        """Attach the voice-placement plane: each node's prober calls
        ``plane.on_probe_cycle(node)`` after every health cycle (the
        reconcile runs on the prober thread at the plane's own slower
        cadence, so a wedged reconcile can only ever stall its own
        node's prober), and ``pick(voice=...)`` restricts routing to
        the plane's converged holders."""
        self._placement = plane

    @property
    def placement(self):
        return self._placement

    # -- fleet cache attachment (ISSUE 16) ------------------------------------
    def attach_fleetcache(self, fleetcache) -> None:
        """Attach the fleet cache tier: ``pick(affinity_key=...)``
        consults it for the rendezvous owner of a cacheable request,
        and each node's prober calls
        ``fleetcache.on_probe_cycle(node)`` after every health cycle
        (hot-set replication rides the prober threads at the tier's own
        slower cadence, like the placement reconciler)."""
        self._fleetcache = fleetcache

    @property
    def fleetcache(self):
        return self._fleetcache

    # -- tenant-config propagation attachment (ISSUE 17) -----------------------
    def attach_tenancy(self, propagator) -> None:
        """Attach the tenant-config propagator: each node's prober
        calls ``propagator.on_probe_cycle(node)`` after every health
        cycle (the desired-state push rides the prober threads at the
        propagator's own slower cadence, like the placement
        reconciler), so every node converges to the router's tenant
        table without a control-plane dependency."""
        self._tenancy_propagator = propagator

    @property
    def tenancy_propagator(self):
        return self._tenancy_propagator

    def routable_nodes(self) -> list:
        """Snapshot of the nodes currently accepting traffic (the
        replication pass targets peers from this list)."""
        with self._lock:
            return [n for n in self.nodes if self._routable_locked(n)]

    def voice_load_view(self, node: MeshNode) -> tuple:
        """(actual loaded-voice set or None, per-voice router-side
        in-flight) for the placement reconciler — one consistent read
        under the router lock."""
        with self._lock:
            return node.loaded_voices, dict(node.voice_outstanding)

    def note_voice_loaded(self, node: MeshNode, voice_id: str) -> None:
        """A voice op just landed on ``node`` (RPC fan-out or a
        reconcile replay): fold it into the actual set optimistically
        so routing converges immediately — the next ``/readyz`` scrape
        remains the source of truth and overwrites the whole set."""
        with self._lock:
            if node.loaded_voices is None:
                node.loaded_voices = frozenset((voice_id,))
            else:
                node.loaded_voices = node.loaded_voices | {voice_id}

    def note_voice_unloaded(self, node: MeshNode,
                            voice_id: str) -> None:
        with self._lock:
            if node.loaded_voices:
                node.loaded_voices = node.loaded_voices - {voice_id}

    def note_reconcile_failure(self, node: MeshNode,
                               reason: str) -> None:
        """A failed reconcile cycle (injected ``mesh.reconcile`` fault,
        hang-cap conviction, failed replay op) counts toward the
        node's breaker on its OWN consecutive counter — probes succeed
        4x as often as reconciles run, so sharing the probe counter
        would let each probe success launder the reconcile failures
        accumulated between cycles (the PR-12 probe-vs-route lesson,
        third edition).  A node whose control plane cannot be
        reconciled is therefore eventually evicted from membership."""
        with self._lock:
            node.consecutive_reconcile_failures += 1
            self._maybe_trip_locked(
                node, node.consecutive_reconcile_failures,
                f"reconcile failed ({reason})")

    def note_reconcile_success(self, node: MeshNode) -> None:
        """A clean reconcile cycle resets only the RECONCILE counter
        (never the probe or route ones)."""
        with self._lock:
            node.consecutive_reconcile_failures = 0

    def begin_voice_retire(self, node: MeshNode,
                           voice_id: str) -> bool:
        """Atomically stop routing ``voice_id`` to ``node`` ahead of an
        unload/eviction op.  Under the router lock: refuse (False) if
        the voice has in-flight streams there; otherwise remove it from
        the node's actual set — ``pick`` can then never route a new
        stream for the voice to this node, so the unload RPC that
        follows cannot kill a stream the router admitted (the
        never-evict-a-live-voice invariant, closed against the
        diff-to-apply race).  A failed unload RPC self-heals: the next
        ``/readyz`` scrape restores the actual set and the reconciler
        retries."""
        with self._lock:
            if node.voice_outstanding.get(voice_id, 0) > 0:
                return False
            if node.loaded_voices:
                node.loaded_voices = node.loaded_voices - {voice_id}
            return True

    def record_scope_scrape(self, node: MeshNode) -> None:
        """One successful scope-export scrape of ``node`` (stamps the
        staleness clock the fleet scraper reads back)."""
        with self._lock:
            node.scope_scrape_at = time.monotonic()

    def scope_scrape_age_s(self, node: MeshNode) -> Optional[float]:
        """Seconds since the node's scope export last scraped OK, or
        None before the first success (the
        ``sonata_mesh_node_scrape_age_seconds`` callback)."""
        with self._lock:
            at = node.scope_scrape_at
        return None if at is None else time.monotonic() - at

    def set_scope_stale(self, node: MeshNode, stale: bool) -> None:
        """Flip the staleness verdict (the FleetScope's eviction lever):
        a stale node is unroutable until a scrape lands again."""
        with self._lock:
            was, node.scope_stale = node.scope_stale, stale
        if stale and not was:
            log.warning(
                "mesh %s: node %s scope-export scrape is stale; evicted "
                "to unroutable until its observability plane answers "
                "again", self.name, node.node_id)
        elif was and not stale:
            log.info("mesh %s: node %s scope-export scrape recovered; "
                     "routable again", self.name, node.node_id)

    # -- membership / health --------------------------------------------------
    def _routable_locked(self, node: MeshNode) -> bool:
        return (node.state != OPEN and node.ready and not node.draining
                and not node.scope_stale)

    def routable_count(self) -> int:
        """Nodes currently accepting traffic (closed or probing breaker,
        ready, not draining) — the router's readiness gate."""
        with self._lock:
            return sum(1 for n in self.nodes if self._routable_locked(n))

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "closed": self._closed,
                    "routable": sum(1 for n in self.nodes
                                    if self._routable_locked(n)),
                    "stats": dict(self.stats),
                    "nodes": [n.snapshot() for n in self.nodes]}

    def probe_once(self, node: MeshNode) -> bool:
        """One health cycle: ``/readyz`` gate, then ``/metrics``
        enrichment (drain flag, occupancy, node id).  Returns whether
        the node's plane answered.  Without a metrics port the cycle is
        a no-op success — membership is then route-outcome-driven."""
        try:
            faults.fire("mesh.health")
            if node.spec.metrics_base is None:
                # no health plane: the cycle is an optimistic success so
                # a breaker tripped by route failures still walks
                # OPEN → HALF_OPEN → trial — without this, a metrics-less
                # node's first trip would be permanent eviction.  The
                # draining flag is preserved as-is (nothing can refute it).
                self._probe_result(node, ok=True, ready=True,
                                   draining=node.draining)
                return True
            code, rbody = self._fetch(node.spec.metrics_base + "/readyz",
                                      self.probe_timeout_s)
        except Exception as e:
            self._probe_result(node, ok=False,
                               error=f"{type(e).__name__}: {e}")
            return False
        ready = code == 200
        draining = False
        reported: Optional[float] = None
        node_id: Optional[str] = None
        #: the node's ACTUAL loaded-voice set — the `voices=` line on
        #: /readyz is authoritative (present-but-empty means "no
        #: voices", explicitly); absent falls back to the
        #: sonata_voice_loaded gauge below, and neither leaves the
        #: actual set unknown (old backends keep PR-12 semantics)
        voices: Optional[frozenset] = None
        for line in rbody.splitlines():
            if line.startswith("voices="):
                raw = line[len("voices="):].strip()
                voices = frozenset(v for v in raw.split(",") if v)
        try:
            mcode, mbody = self._fetch(
                node.spec.metrics_base + "/metrics", self.probe_timeout_s)
            if mcode == 200:
                # pre-filter to the four families the prober consumes:
                # regex-parsing a node's whole exposition every probe
                # interval burns router-process GIL time that lands on
                # TTFB-critical chunk relays (measured by bench_mesh)
                wanted = [line for line in mbody.splitlines()
                          if line.startswith(_SCRAPE_FAMILIES)]
                series = parse_prometheus_text("\n".join(wanted))
                draining = any(v > 0 for _l, v in
                               series.get("sonata_draining", []))
                outs = [v for _l, v in
                        series.get("sonata_replica_outstanding", [])]
                if not outs:
                    outs = [v for _l, v in
                            series.get("sonata_in_flight", [])]
                if outs:
                    reported = float(sum(outs))
                for lbl, _v in series.get("sonata_node_info", []):
                    if lbl.get("node_id"):
                        node_id = lbl["node_id"]
                if voices is None:
                    loaded = series.get("sonata_voice_loaded", [])
                    if loaded:
                        voices = frozenset(
                            lbl["voice"] for lbl, v in loaded
                            if v > 0 and lbl.get("voice"))
        except Exception:
            # /readyz answered, so the node is alive; the /metrics
            # enrichment is best-effort and must not convict it
            pass
        self._probe_result(node, ok=True, ready=ready, draining=draining,
                           reported=reported, node_id=node_id,
                           voices=voices)
        return True

    def _probe_result(self, node: MeshNode, *, ok: bool,
                      ready: bool = False, draining: bool = False,
                      reported: Optional[float] = None,
                      node_id: Optional[str] = None,
                      voices: Optional[frozenset] = None,
                      error: Optional[str] = None) -> None:
        with self._lock:
            node.last_probe_at = time.monotonic()
            if not ok:
                node.probe_failures += 1
                self.stats["probe_failures"] += 1
                node.consecutive_probe_failures += 1
                self._maybe_trip_locked(
                    node, node.consecutive_probe_failures,
                    f"health probe failed ({error})")
                return
            if node.draining and not draining and ready:
                log.info("mesh %s: node %s finished draining and is "
                         "ready; rejoining membership", self.name,
                         node.node_id)
            elif draining and not node.draining:
                log.info("mesh %s: node %s reports draining; evicted "
                         "from membership until it rejoins", self.name,
                         node.node_id)
            node.ready = ready
            node.draining = draining
            if reported is not None:
                node.reported_outstanding = reported
            if node_id:
                node.node_id = node_id
            if voices is not None:
                # the scraped actual set replaces the optimistic view
                # wholesale — a restarted node's empty set is real news
                node.loaded_voices = voices
            # a probe success resets only the PROBE counter: it must
            # not launder route failures accumulated between scrapes
            node.consecutive_probe_failures = 0
            if node.state == OPEN and ready and not draining:
                now = time.monotonic()
                if node.next_probe_at is None or now >= node.next_probe_at:
                    node.state = HALF_OPEN
                    log.info("mesh %s: node %s answered its health probe; "
                             "half-open — next routed request is its "
                             "trial", self.name, node.node_id)

    def _maybe_trip_locked(self, node: MeshNode, consecutive: int,
                           reason: str) -> None:
        """Shared trip arithmetic (lock held); ``consecutive`` is the
        caller's own failure-class counter, already incremented."""
        failed_trial = node.state == HALF_OPEN
        if failed_trial or (node.state == CLOSED
                            and consecutive >= self.breaker_threshold):
            self._trip_locked(node, failed_trial=failed_trial,
                              reason=reason)
        elif node.state == OPEN:
            # already out: back the next half-open check off further
            node.probe_backoff_s = min(
                (node.probe_backoff_s or self.probe_interval_s) * 2,
                self.probe_max_s)
            node.next_probe_at = (time.monotonic()
                                  + self._jittered(node.probe_backoff_s))

    def _trip_locked(self, node: MeshNode, *, failed_trial: bool,
                     reason: str) -> None:
        node.state = OPEN
        node.opened_at = time.monotonic()
        if failed_trial and node.probe_backoff_s is not None:
            node.probe_backoff_s = min(node.probe_backoff_s * 2,
                                       self.probe_max_s)
        else:
            node.probe_backoff_s = self.probe_interval_s
        node.next_probe_at = (node.opened_at
                              + self._jittered(node.probe_backoff_s))
        self.stats["breaker_opens"] += 1
        log.error("mesh %s: node %s circuit-broken (%s; next half-open "
                  "check in %.1fs)", self.name, node.node_id, reason,
                  node.probe_backoff_s)

    @staticmethod
    def _jittered(seconds: float) -> float:
        return seconds * (1.0 + MESH_JITTER * random.random())

    def _probe_loop(self, node: MeshNode) -> None:
        while not self._closed:
            try:
                self.probe_once(node)
            except Exception:
                log.exception("mesh %s: probe loop error (node %s)",
                              self.name, node.node_id)
            fleet = self._fleet
            if fleet is not None:
                try:
                    fleet.on_probe_cycle(node)
                except Exception:
                    # the aggregation plane must never stall membership
                    log.exception("mesh %s: fleet scrape error (node %s)",
                                  self.name, node.node_id)
            placement = self._placement
            if placement is not None:
                try:
                    # run_cycle already charges failures to the node's
                    # breaker; this guard only catches plane bugs
                    placement.on_probe_cycle(node)
                except Exception:
                    log.exception(
                        "mesh %s: placement reconcile error (node %s)",
                        self.name, node.node_id)
            fleetcache = self._fleetcache
            if fleetcache is not None:
                try:
                    # replication is advisory anti-entropy: failures
                    # are counted inside, this guard catches tier bugs
                    fleetcache.on_probe_cycle(node)
                except Exception:
                    log.exception(
                        "mesh %s: fleet-cache replication error "
                        "(node %s)", self.name, node.node_id)
            propagator = self._tenancy_propagator
            if propagator is not None:
                try:
                    # config push is idempotent desired-state: failures
                    # are counted inside, this guard catches plane bugs
                    propagator.on_probe_cycle(node)
                except Exception:
                    log.exception(
                        "mesh %s: tenant-config push error (node %s)",
                        self.name, node.node_id)
            self._wake.wait(timeout=self.probe_interval_s)

    # -- routing --------------------------------------------------------------
    @staticmethod
    def _headroom(node: MeshNode) -> float:
        """Slots left below the backend's current graduated batch rung,
        from router + scraped occupancy: a node at 3 of rung 4 (headroom
        1) beats one at 2 of rung 2 (headroom 0) — the new stream fills
        a rung there instead of graduating one."""
        occupancy = node.outstanding + node.reported_outstanding
        for rung in BATCH_BUCKETS:
            if rung >= max(occupancy, 1.0):
                return rung - occupancy
        return 0.0

    def _rank_locked(self, node: MeshNode) -> tuple:
        return (node.outstanding, -self._headroom(node), node.index)

    def pick(self, exclude: tuple = (),
             voice: Optional[str] = None,
             affinity_key: Optional[str] = None) -> MeshNode:
        """Reserve the best routable node (caller must :meth:`release`).

        A half-open node with nothing outstanding takes the request as
        its breaker trial.  With ``voice`` set and a placement plane
        attached, candidates are restricted to converged holders of
        that voice; zero converged holders of a known voice raises the
        typed :class:`VoiceWarming` refusal (``route_stream`` absorbs
        it with the bounded placement wait).  With ``affinity_key`` set
        and a fleet cache attached, the key's rendezvous owner among
        the healthy candidates wins (unless its load skew trips the
        guard) — trial precedence and every exclusion/restriction
        above still apply, so affinity only ever biases WITHIN the
        routable set.  Raises typed :class:`Draining` when every
        candidate is mid-deploy, :class:`Overloaded` when none is
        healthy."""
        with self._lock:
            allowed = None
            if voice is not None and self._placement is not None:
                # plane lock nested inside the router lock — the one
                # ordering the placement plane is built around
                allowed = self._placement.routable_for(voice)

            def _holds(n: MeshNode) -> bool:
                return allowed is None or n.index in allowed

            for n in self.nodes:
                if (n.state == HALF_OPEN and n.outstanding == 0
                        and n.ready and not n.draining
                        and not n.scope_stale and n not in exclude
                        and _holds(n)):
                    return self._reserve_locked(n, voice)
            routable = [n for n in self.nodes
                        if n.state == CLOSED and n.ready
                        and not n.draining and not n.scope_stale
                        and n not in exclude and _holds(n)]
            if not routable:
                candidates = [n for n in self.nodes if n not in exclude]
                if allowed is not None and any(
                        self._routable_locked(n) for n in candidates) \
                        and not any(_holds(n) for n in candidates
                                    if self._routable_locked(n)):
                    # healthy nodes exist, none has converged on the
                    # voice yet: warming, not overload
                    raise VoiceWarming(
                        f"voice-warming: no converged holder of voice "
                        f"{voice!r} in mesh {self.name!r} yet "
                        "(placement replay in flight; retry shortly)")
                if candidates and all(n.draining for n in candidates):
                    raise Draining(
                        f"draining: every node of mesh {self.name!r} is "
                        "draining for a deploy; retry shortly")
                raise Overloaded(
                    f"mesh {self.name!r}: no healthy node available "
                    f"({sum(1 for n in self.nodes if self._routable_locked(n))}"
                    f" of {len(self.nodes)} routable)")
            if affinity_key is not None and self._fleetcache is not None:
                choice = self._fleetcache.affinity_choice_locked(
                    affinity_key, routable)
                if choice is not None:
                    return self._reserve_locked(choice, voice)
            best = min(routable, key=self._rank_locked)
            return self._reserve_locked(best, voice)

    def _reserve_locked(self, node: MeshNode,
                        voice: Optional[str]) -> MeshNode:
        node.outstanding += 1
        node.routed += 1
        self.stats["routed"] += 1
        if voice is not None:
            node.voice_outstanding[voice] = \
                node.voice_outstanding.get(voice, 0) + 1
            if self._placement is not None:
                self._placement.touch(voice)  # the LRU clock
        return node

    def release(self, node: MeshNode,
                voice: Optional[str] = None) -> None:
        with self._lock:
            if node.outstanding > 0:
                node.outstanding -= 1
            if voice is not None:
                held = node.voice_outstanding.get(voice, 0)
                if held <= 1:
                    node.voice_outstanding.pop(voice, None)
                else:
                    node.voice_outstanding[voice] = held - 1

    def record_route(self, node: MeshNode, ok: bool,
                     reason: str = "") -> None:
        """Route outcome → breaker bookkeeping (success closes a
        half-open trial; failure counts toward the threshold)."""
        with self._lock:
            if ok:
                node.consecutive_failures = 0
                if node.state == HALF_OPEN:
                    node.state = CLOSED
                    node.probe_backoff_s = None
                    self.stats["recovered"] += 1
                    log.info("mesh %s: node %s trial request succeeded; "
                             "breaker closed", self.name, node.node_id)
            else:
                node.route_failures += 1
                node.consecutive_failures += 1
                self._maybe_trip_locked(node, node.consecutive_failures,
                                        reason or "route failure")

    def _note_draining(self, node: MeshNode, exc: BaseException) -> None:
        """A typed draining refusal evicts the node NOW — the next
        scrape would too, but requests racing the deploy should not
        keep landing on it for a probe interval."""
        with self._lock:
            if not node.draining:
                node.draining = True
                log.info("mesh %s: node %s refused typed draining (%s); "
                         "evicted from membership until it rejoins",
                         self.name, node.node_id, exc)

    @staticmethod
    def _cancel(call) -> None:
        cancel = getattr(call, "cancel", None)
        if cancel is not None:
            try:
                cancel()
            except Exception:
                pass

    def _hedge_fire(self, call, hedged: list, got_first: list,
                    lock: threading.Lock) -> None:
        # the flag exchange under the lock makes the hedge and the
        # first chunk mutually exclusive: once got_first is set the
        # timer is a no-op, so a cancel can never land after bytes
        # were yielded to the client
        with lock:
            if got_first[0]:
                return
            hedged[0] = True
        self._cancel(call)

    def route_stream(self, start: Callable, *,
                     deadline: Optional[Deadline] = None,
                     request_id: Optional[str] = None,
                     classify: Optional[Callable] = None,
                     voice: Optional[str] = None,
                     affinity_key: Optional[str] = None) -> Iterator:
        """Route one streaming request across the fleet; yields chunks.

        ``start(node, timeout_s)`` opens the stream on ``node`` and
        returns an iterable (``cancel()`` honored when present —
        real gRPC calls and the test fakes both have one).  The retry
        contract: route-class failures and draining refusals reroute
        (bounded by ``SONATA_MESH_RETRIES`` and the deadline) while no
        chunk has been yielded; after the first chunk every failure is
        typed through.  With ``voice`` set, routing is restricted to
        converged placement holders, and a :class:`VoiceWarming` state
        gets a bounded router-side wait (``SONATA_PLACEMENT_WAIT_MS``,
        separate from the retry budget — a warming voice is not a
        fault) before failing typed.  The caller holds its own
        admission slot; this method holds the per-node outstanding
        count.  ``affinity_key`` (the fleet cache tier) biases every
        attempt's pick toward the key's rendezvous owner — on failover
        the dead owner sits in the exclusion list, so HRW over the
        remaining nodes lands on the key's next preference, which is
        exactly the hot-set replication peer.
        """
        classify = classify if classify is not None else default_classify
        tried: list = []
        retries_left = self.retries
        backoff_s = self.retry_backoff_ms / 1e3
        streamed = False
        warming_until: Optional[float] = None
        while True:
            if deadline is not None:
                deadline.raise_if_expired()
            try:
                node = self.pick(exclude=tuple(tried), voice=voice,
                                 affinity_key=affinity_key)
            except VoiceWarming as e:
                now = time.monotonic()
                if warming_until is None:
                    budget = (self._placement.wait_budget_s
                              if self._placement is not None else 0.0)
                    warming_until = now + budget
                if now < warming_until and (deadline is None
                                            or deadline.alive()):
                    time.sleep(min(0.05, max(warming_until - now, 0.0)))
                    continue
                with self._lock:
                    self.stats["failed"] += 1
                log.warning("mesh %s: request %s failed voice-warming "
                            "after the placement wait budget (%s)",
                            self.name, request_id, e)
                raise
            except (Overloaded, Draining) as e:
                # transient no-candidate states deserve the same bounded
                # retry as a route failure: the canonical case is a node
                # kill while the only peer is HALF_OPEN with its trial
                # in flight — the trial resolves in one request's time,
                # well inside a backoff step
                if retries_left > 0 and (deadline is None
                                         or deadline.alive()):
                    retries_left -= 1
                    delay = backoff_s * (1.0 + MESH_JITTER
                                         * random.random())
                    log.warning("mesh %s: no candidate node for request "
                                "%s (%s); retrying in %.0f ms", self.name,
                                request_id, e, delay * 1e3)
                    time.sleep(delay)
                    backoff_s = min(backoff_s * 2, MAX_RETRY_BACKOFF_S)
                    continue
                with self._lock:
                    self.stats["failed"] += 1
                raise
            timeout_s = None
            if deadline is not None:
                rem = deadline.remaining()
                if rem is not None:
                    # shrunk by everything spent so far: queue wait,
                    # failed attempts, backoff sleeps
                    timeout_s = max(rem, 1e-3)
            call = None
            hedged = [False]
            got_first = [False]
            hedge_lock = threading.Lock()
            timer: Optional[threading.Timer] = None
            try:
                with tracing.span("mesh-dispatch", node=node.node_id,
                                  addr=node.spec.addr,
                                  attempt=len(tried) + 1) as sp:
                    faults.fire("mesh.route")
                    call = start(node, timeout_s)
                    it = iter(call)
                    if self.hedge_ms > 0:
                        timer = threading.Timer(
                            self.hedge_ms / 1e3, self._hedge_fire,
                            (call, hedged, got_first, hedge_lock))
                        timer.daemon = True
                        timer.start()
                    try:
                        first = next(it, _DONE)
                    finally:
                        if timer is not None:
                            timer.cancel()
                    if timer is not None:
                        with hedge_lock:
                            got_first[0] = True
                            hedge_won = hedged[0]
                        if hedge_won and first is not _DONE:
                            # the timer cancelled the call concurrently
                            # with the first chunk arriving; nothing
                            # reached the client yet, and the rest of
                            # the stream is gone — reroute instead of
                            # emitting one chunk of a dead stream
                            raise _HedgeCancelled(
                                "first-chunk hedge fired at "
                                f"{self.hedge_ms:g} ms")
                    if first is not _DONE:
                        streamed = True
                        yield first
                        for chunk in it:
                            yield chunk
                    sp.annotate(streamed=streamed)
                self.record_route(node, ok=True)
                self.release(node, voice)
                return
            except GeneratorExit:
                # the client went away: stop the backend stream, free
                # the slot, and let the generator close normally
                self._cancel(call)
                self.release(node, voice)
                raise
            except Exception as e:
                self.release(node, voice)
                if hedged[0] and not streamed:
                    kind = "hedge"
                elif streamed:
                    kind = "fatal"
                else:
                    kind = classify(e)
                reason = f"{type(e).__name__}: {e}"
                if kind == "draining":
                    # a deploy, not a fault: evict, don't count
                    self._note_draining(node, e)
                elif kind in ("route", "hedge"):
                    self.record_route(node, ok=False, reason=reason)
                else:
                    if streamed:
                        # mid-stream death is the node's fault — count
                        # it, but the client already holds bytes: fail
                        # typed rather than resend audio
                        self.record_route(node, ok=False, reason=reason)
                    with self._lock:
                        self.stats["failed"] += 1
                    raise
                tried.append(node)
                if retries_left <= 0 or (deadline is not None
                                         and not deadline.alive()):
                    with self._lock:
                        self.stats["failed"] += 1
                    raise
                retries_left -= 1
                with self._lock:
                    self.stats["rerouted"] += 1
                    if kind == "draining":
                        self.stats["rerouted_draining"] += 1
                    elif kind == "hedge":
                        self.stats["hedged"] += 1
                ctx = tracing.current()
                if ctx is not None:
                    # the failover must be visible in the request's own
                    # trace, like the pool's resubmit span
                    trace, parent = ctx
                    now = time.monotonic()
                    trace.new_span("mesh-reroute", parent=parent,
                                   start=now, end=now,
                                   attrs={"failed_node": node.node_id,
                                          "kind": kind, "error": reason})
                log.warning("mesh %s: rerouting request %s off node %s "
                            "(%s: %s)", self.name, request_id,
                            node.node_id, kind, e)
                if kind == "route":
                    delay = backoff_s * (1.0 + MESH_JITTER
                                         * random.random())
                    if deadline is not None:
                        rem = deadline.remaining()
                        if rem is not None:
                            delay = min(delay, max(rem - 0.01, 0.0))
                    if delay > 0:
                        time.sleep(delay)
                    backoff_s = min(backoff_s * 2, MAX_RETRY_BACKOFF_S)
                continue
