"""Serving runtime: admission control, deadlines, metrics, and health.

The layer every production inference stack grows once it must survive
overload and be observed in production (ROADMAP north star: heavy traffic
from millions of users).  Four orthogonal pieces:

- :mod:`.admission` — bounded admission (max in-flight + max queue
  depth); excess load fails fast with :class:`Overloaded` instead of
  queueing unboundedly.
- :mod:`.deadlines` — per-request :class:`Deadline` propagated into the
  batch scheduler, so expired or client-abandoned work is dropped
  *before* it reaches a device dispatch.
- :mod:`.metrics` — counter/gauge/histogram registry with Prometheus
  text exposition over a stdlib HTTP server.
- :mod:`.health` — liveness plus warmup-gated readiness for rolling
  restarts.
- :mod:`.tracing` — request-scoped span trees (Dapper-style) with
  coalesced-dispatch attribution, ring-buffered and served from the
  same HTTP plane at ``/debug/traces`` / ``/debug/slowest``.
- :mod:`.faults` — first-party failpoint injection (named sites, armed
  via ``SONATA_FAILPOINTS`` or ``/debug/failpoints``), the substrate the
  chaos smoke drives.
- :mod:`.degradation` — the graceful-degradation ladder: sustained
  shedding or watchdog fires move the process through named levels
  (shrink coalescing → reject batch work → readiness off), recovering
  by hysteresis.

:class:`ServingRuntime` bundles one of each with the standard instrument
set and the glue that exports existing observability (``RtfCounter``,
``dispatch_stats()``, scheduler stats) per voice.  Frontends construct
one runtime per process and thread it through their request paths; the
whole layer is frontend-agnostic — nothing in here imports gRPC.
"""

from __future__ import annotations

import time
from typing import Optional

from . import degradation as degradation_mod
from . import faults, tracing
from . import ledger as ledger_mod
from . import mesh as mesh_mod
from . import scope as scope_mod
from . import synthcache as synthcache_mod
from . import tenancy as tenancy_mod
from . import warmup as warmup_mod
from .admission import AdmissionController, Overloaded
from .deadlines import Deadline, DeadlineExceeded, default_timeout_s
from .degradation import DegradationLadder
from .drain import DrainCoordinator, Draining
from .faults import InjectedFault
from .health import HealthState
from .ledger import RequestLedger
from .metrics import (
    MetricsRegistry,
    parse_prometheus_text,
    resolve_metrics_port,
    start_http_server,
)
from .placement import PlacementPlane, VoiceWarming
from .replicas import ReplicaPool, resolve_replica_count
from .scope import Scope
from .synthcache import SynthCache
from .tracing import Trace, Tracer

__all__ = [
    "AdmissionController",
    "Overloaded",
    "Deadline",
    "DeadlineExceeded",
    "DegradationLadder",
    "DrainCoordinator",
    "Draining",
    "InjectedFault",
    "default_timeout_s",
    "degradation_mod",
    "faults",
    "ledger_mod",
    "RequestLedger",
    "HealthState",
    "mesh_mod",
    "MetricsRegistry",
    "parse_prometheus_text",
    "resolve_metrics_port",
    "start_http_server",
    "PlacementPlane",
    "ReplicaPool",
    "resolve_replica_count",
    "Scope",
    "SynthCache",
    "VoiceWarming",
    "scope_mod",
    "synthcache_mod",
    "tenancy_mod",
    "ServingRuntime",
    "Trace",
    "Tracer",
    "tracing",
    "warmup_mod",
]


class ServingRuntime:
    """One process's serving plane: admission + deadlines + metrics +
    health, pre-wired with the standard instrument set."""

    def __init__(self, *, max_in_flight: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 scope: Optional[Scope] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.health = HealthState(registry=self.registry)
        self.admission = AdmissionController(max_in_flight, max_queue_depth)
        #: request-scoped tracing: the process-wide default tracer unless
        #: one is injected (tests), so every frontend and the HTTP debug
        #: plane share one ring buffer
        self.tracer = tracer if tracer is not None else \
            tracing.default_tracer()
        #: server-side default when the client sets no deadline; None
        #: disables the default (explicit arg > env > 120 s).  An
        #: explicit <= 0 means "disabled" — same contract as the env
        #: knob — NOT "already expired".
        if request_timeout_s is None:
            self.request_timeout_s = default_timeout_s()
        else:
            self.request_timeout_s = (request_timeout_s
                                      if request_timeout_s > 0 else None)
        self.http: Optional["object"] = None
        #: per-voice labeled series created by register_voice, so
        #: unregister_voice removes exactly what was registered (no
        #: twin hardcoded name lists to keep in sync)
        self._voice_series: dict = {}

        r = self.registry
        self.requests = r.counter(
            "sonata_requests_total", "Requests admitted, by rpc.")
        self.failures = r.counter(
            "sonata_request_failures_total",
            "Requests failed, by rpc and grpc code.")
        self.shed = r.counter(
            "sonata_shed_total",
            "Requests rejected at admission (RESOURCE_EXHAUSTED).")
        self.expired = r.counter(
            "sonata_deadline_expired_total",
            "Requests or scheduler items dropped on an expired deadline.")
        self.ttfb = r.histogram(
            "sonata_ttfb_seconds",
            "Time to first audio of a synthesis stream.")
        self.synth_latency = r.histogram(
            "sonata_synth_seconds",
            "End-to-end synthesis request latency.")
        r.gauge(
            "sonata_in_flight",
            "Admitted requests currently held (executing or queued)."
        ).set_function(lambda: float(self.admission.in_flight))
        r.gauge(
            "sonata_admission_capacity",
            "Admission ceiling (max_in_flight + max_queue_depth)."
        ).set_function(lambda: float(self.admission.capacity))
        # admission sheds counted inside the controller surface here too,
        # so dashboards need only one source
        self.shed.labels(source="admission").set_function(
            lambda: float(self.admission.shed_total))
        self._started_at = time.monotonic()
        r.gauge("sonata_uptime_seconds", "Seconds since runtime start."
                ).set_function(
            lambda: time.monotonic() - self._started_at)
        #: stable node identity for the fleet tier (ISSUE 12): set by
        #: the frontend once it knows its bind address, via set_node_id
        self.node_id: Optional[str] = None
        #: fleet aggregation plane (ISSUE 13): set by the mesh router
        #: frontend before start_http, so the HTTP plane serves
        #: /debug/fleet and /debug/traces/stitched; None on backend
        #: nodes (they only *export*, via /debug/scope/export)
        self.fleet = None
        #: graceful drain (ISSUE 9): the process-wide drain flag + phase
        #: log + bounded in-flight wait; frontends' admission paths
        #: consult it so new work mid-drain fails typed (UNAVAILABLE,
        #: never RESOURCE_EXHAUSTED — a deploy is not overload)
        self.drain = DrainCoordinator()
        r.gauge(
            "sonata_draining",
            "1 while the process is draining for a restart (readiness "
            "off, new admissions refused typed), else 0."
        ).set_function(lambda: 1.0 if self.drain.draining else 0.0)
        #: bucket-lattice warmup progress (ISSUE 9): 0 → 1 as the boot
        #: warmup compiles its enumerated shapes; a gauge stuck below
        #: 1.0 is a wedged or over-budget warmup
        self.warmup_progress = warmup_mod.WarmupProgress()
        r.gauge(
            "sonata_warmup_progress",
            "Bucket-lattice warmup progress (0 at boot, done/total "
            "while compiling, 1 once warm; readiness waits for it)."
        ).set_function(self.warmup_progress.fraction)
        #: graceful-degradation ladder: admission sheds feed it directly;
        #: deep layers (scheduler queue-full, pool no-healthy, watchdog)
        #: feed the process-global install.  The gauge read doubles as
        #: the lazy hysteresis tick — every scrape decays a quiet ladder.
        self.degradation = DegradationLadder()
        degradation_mod.install(self.degradation)
        self.admission.on_shed = self.degradation.record_shed
        r.gauge(
            "sonata_degradation_level",
            "Graceful-degradation ladder level (0 normal, 1 shrink "
            "coalescing, 2 reject batch work, 3 readiness off)."
        ).set_function(lambda: float(self.degradation.current_level()))
        #: level 3 takes the process out of the serving set; recovery
        #: (hysteresis) flips /readyz back with no operator action
        self.health.add_readiness_gate(
            "degradation", lambda: self.degradation.current_level() < 3)
        #: chaos observability: series appear once a failpoint registry
        #: exists (counter semantics via scrape-time callbacks, like the
        #: replica series)
        fp = r.counter(
            "sonata_failpoint_fires_total",
            "Injected-fault firings since process start, by site.")
        for site in faults.SITES:
            fp.labels(site=site).set_function(
                lambda s=site: faults.fires_total(s))
        #: sonata-scope aggregation plane (ISSUE 7): rolling per-stage
        #: quantiles, SLO burn rates, dispatch padding-waste accounting,
        #: and the 1 Hz flight recorder.  SONATA_SCOPE=0 disables; the
        #: hooks then cost one module-global read.  Installed globally
        #: (like the ladder) so the scheduler and tracer feed it.
        self.scope: Optional[Scope] = None
        if scope is not None or scope_mod.scope_enabled():
            self.scope = scope if scope is not None else Scope()
            scope_mod.install(self.scope)
            self.scope.bind_metrics(r)
            self.scope.add_probe(
                "in_flight", lambda: float(self.admission.in_flight))
            self.scope.add_probe(
                "shed_total", lambda: float(self.admission.shed_total))
            self.scope.start()
        #: content-addressed synthesis cache (ISSUE 15): enabled by
        #: SONATA_SYNTH_CACHE_MB > 0 (default off — the request path is
        #: then byte-for-byte the pre-cache shape).  The frontends probe
        #: it ahead of pool/iteration-loop admission; its hit/miss/
        #: bytes series ride the metrics plane as scrape-time callbacks
        #: and its hit-ratio rows ride the scope plane.
        self.synth_cache: Optional[SynthCache] = synthcache_mod.from_env()
        if self.synth_cache is not None:
            self.synth_cache.bind_metrics(r)
            if self.scope is not None:
                self.scope.attach_cache_stats(self.synth_cache.cache_view)
                self.scope.add_probe(
                    "cache_hit_ratio",
                    lambda: self.synth_cache.hit_ratio())
                self.scope.add_probe(
                    "cache_bytes",
                    lambda: float(self.synth_cache.bytes_used))
        #: tenant control plane (ISSUE 17): enabled by SONATA_TENANTS
        #: (default off — every RPC path is then byte-for-byte the
        #: pre-tenancy shape, pinned).  The fair gate sizes its slots to
        #: the admission controller's in-flight ceiling: below it entry
        #: is immediate, at it the DRR queues take over.
        self.tenancy: Optional[tenancy_mod.TenantPlane] = \
            tenancy_mod.from_env(fair_slots=self.admission.max_in_flight)
        if self.tenancy is not None:
            self.tenancy.bind_metrics(r)
            self.shed.labels(source="tenancy").set_function(
                lambda: sum(self.tenancy.stat(t, "shed")
                            for t in self.tenancy.tenant_names()))
            if self.scope is not None:
                # padding-waste chargeback: the scope pro-rates each
                # dispatch's waste over the tenants running synthesis
                # at that moment
                self.scope.attach_tenant_mix(self.tenancy.active_mix)
            if self.synth_cache is not None:
                # per-tenant insert budgets: a tenant's committed bytes
                # are bounded to cache_share x SONATA_SYNTH_CACHE_MB
                # (tenancy never joins the cache KEY — identical text
                # still dedups across tenants)
                self.synth_cache.set_share_resolver(
                    self.tenancy.cache_share)
        #: per-request wide-event ledger (ISSUE 19): enabled by
        #: SONATA_LEDGER_MB > 0 (default off — the request path is then
        #: byte-for-byte the pre-ledger shape and zero new metric
        #: series exist).  Frontends begin/emit records; the ring is
        #: served by GET /debug/requests on the metrics plane.
        self.ledger: Optional[RequestLedger] = ledger_mod.from_env()
        if self.ledger is not None:
            self.ledger.bind_metrics(r)
        #: per-voice flight-recorder probes added by register_voice, so
        #: unregister removes exactly what was added
        self._voice_probes: dict = {}

    # -- node identity (fleet tier) ------------------------------------------
    def set_node_id(self, node_id: str) -> None:
        """Stable node identity (``SONATA_NODE_ID`` or the bind
        ``host:port``): exported as ``sonata_node_info{node_id=...}``,
        appended to ``/readyz``, answered in ``CheckHealth``, and
        stamped into gRPC trailing metadata — so sonata-mesh router
        logs/spans name the backend that served each request instead of
        an opaque channel."""
        self.node_id = node_id
        self.health.node_id = node_id
        if self.ledger is not None:
            # every subsequent record names the node that served it
            self.ledger.node_id = node_id
        self.registry.gauge(
            "sonata_node_info",
            "Constant 1, labeled with this process's stable node_id "
            "(SONATA_NODE_ID, default the gRPC bind host:port)."
        ).labels(node_id=node_id).set(1.0)

    # -- graceful drain ------------------------------------------------------
    def begin_drain(self, reason: str = "shutdown") -> bool:
        """Enter the drain state: readiness flips off FIRST (the load
        balancer stops routing here before anything tears down), then
        the admission paths refuse new work typed.  First caller wins;
        returns whether this call started the drain."""
        first = self.drain.begin(reason)
        if first:
            self.health.set_not_ready(f"draining: {reason}")
        return first

    # -- deadlines -----------------------------------------------------------
    def deadline_for(self, context=None) -> Deadline:
        """Per-request deadline: client gRPC deadline > server default."""
        if context is None:
            return Deadline.after(self.request_timeout_s)
        return Deadline.from_grpc_context(
            context, default_s=self.request_timeout_s)

    # -- HTTP plane ----------------------------------------------------------
    def start_http(self, port: Optional[int] = None,
                   host: Optional[str] = None) -> Optional[int]:
        """Start the /metrics + /healthz + /readyz server if configured.

        Returns the bound port, or None when disabled (no explicit port
        and no ``SONATA_METRICS_PORT``)."""
        resolved = resolve_metrics_port(port)
        if resolved is None:
            return None
        self.http = start_http_server(self.registry, health=self.health,
                                      port=resolved, host=host,
                                      tracer=self.tracer, scope=self.scope,
                                      fleet=self.fleet,
                                      tenancy=self.tenancy,
                                      ledger=self.ledger)
        return self.http.port

    @property
    def http_port(self) -> Optional[int]:
        return self.http.port if self.http is not None else None

    # -- per-voice observability wiring --------------------------------------
    def register_voice(self, voice_id: str, *, rtf_counter=None,
                       dispatch_stats=None, scheduler=None,
                       replica_pool=None) -> None:
        """Export an existing voice's counters as labeled gauge series.

        Everything is callback-based: the scrape reads live state, the
        hot path pays nothing.  ``dispatch_stats`` is the zero-arg
        callable from ``PiperVoice.dispatch_stats`` /
        ``SpeechSynthesizer.dispatch_stats``.  ``replica_pool`` adds the
        per-replica series (outstanding, dispatches, breaker state,
        device id) and pool-level routing counters.
        """
        r = self.registry
        lbl = {"voice": voice_id}
        owned = self._voice_series.setdefault(voice_id, [])

        def labeled_gauge(name, help, fn, labels):
            metric = r.gauge(name, help)
            metric.labels(**labels).set_function(fn)
            owned.append((metric, labels))

        def voice_gauge(name, help, fn):
            labeled_gauge(name, help, fn, lbl)

        # actual-state signal for the fleet tier (ISSUE 14): the
        # sonata-mesh placement reconciler scrapes this gauge (and the
        # /readyz ``voices=`` twin maintained on the health plane) to
        # diff a node's resident voices against its desired state
        self.health.note_voice(voice_id)
        voice_gauge("sonata_voice_loaded",
                    "1 while this voice is loaded and serving on this "
                    "node (the actual-state signal the sonata-mesh "
                    "placement reconciler diffs against desired state).",
                    lambda: 1.0)
        if rtf_counter is not None:
            def stat(attr):
                return lambda: float(getattr(rtf_counter.snapshot(), attr))

            voice_gauge("sonata_voice_utterances",
                        "Utterances synthesized, per voice.",
                        stat("utterances"))
            voice_gauge("sonata_voice_rtf",
                        "Aggregate real-time factor, per voice "
                        "(inference ms / audio ms).",
                        lambda: float(rtf_counter.snapshot().rtf))
            voice_gauge("sonata_voice_audio_ms",
                        "Total audio milliseconds synthesized, per voice.",
                        stat("audio_ms"))
        if dispatch_stats is not None:
            def stage_stat(stage, key):
                def read():
                    stats = dispatch_stats()
                    s = (stats or {}).get(stage)
                    return float(s[key]) if s else None
                return read

            for stage in ("stream_decode", "stream_stage"):
                for key in ("requests", "dispatches"):
                    voice_gauge(f"sonata_{stage}_{key}",
                                f"Stream coalescer {key}, per voice.",
                                stage_stat(stage, key))
        if self.scope is not None:
            # dispatch padding-waste accumulator (scope plane): counter
            # semantics via a scrape-time callback, like the replica
            # series; the scope keys on the voice label the scheduler
            # stamps into its dispatch attribution
            waste = r.counter(
                "sonata_dispatch_padding_waste_seconds_total",
                "Device-dispatch seconds spent on padding rows "
                "(dispatch duration x padding_ratio, accumulated), "
                "per voice.")
            waste.labels(**lbl).set_function(
                lambda v=voice_id: self.scope.padding_waste_seconds(v))
            owned.append((waste, lbl))
            # cold-compile containment: compiles AFTER warmup completion
            # are lattice-coverage regressions — zero under smoke
            # traffic is the acceptance bar, and any nonzero value also
            # ships a flight-recorder incident
            cold = r.counter(
                "sonata_runtime_cold_compiles_total",
                "Device dispatches that paid an XLA compile after the "
                "boot warmup completed (warmup-lattice coverage holes), "
                "per voice.")
            cold.labels(**lbl).set_function(
                lambda v=voice_id: self.scope.runtime_cold_compiles(v))
            owned.append((cold, lbl))
        if scheduler is not None:
            voice_gauge("sonata_scheduler_queue_depth",
                        "Items waiting in the batch scheduler, per voice.",
                        lambda: float(scheduler.queue_depth()))
            if self.scope is not None:
                # flight-recorder probes ride the same registration so
                # the timeline names the voice's queue
                probes = self._voice_probes.setdefault(voice_id, [])
                name = f"queue_depth:{voice_id}"
                self.scope.add_probe(
                    name, lambda: float(scheduler.queue_depth()))
                probes.append(name)

            # stats_view() instead of raw .stats: a ReplicaPool passed as
            # the voice's scheduler aggregates its per-replica scheduler
            # counters under the same keys
            def sched_stat(key):
                return lambda: float(scheduler.stats_view().get(key, 0))

            for key, help in (
                    ("requests", "Scheduler items submitted"),
                    ("dispatches", "Scheduler device dispatches"),
                    ("expired", "Scheduler items dropped on expired "
                                "deadlines"),
                    ("cancelled", "Scheduler items dropped on client "
                                  "cancellation"),
                    ("shed", "Scheduler items rejected on a full queue"),
                    ("stuck", "Scheduler dispatches killed by the "
                              "hung-dispatch watchdog")):
                voice_gauge(f"sonata_scheduler_{key}",
                            f"{help}, per voice.", sched_stat(key))
            # time-in-queue histogram (the observability gap the
            # shed/expired counters left): both BatchScheduler and
            # ReplicaPool expose .queue_wait, the pool's aggregated
            # across its replicas' schedulers
            queue_wait = getattr(scheduler, "queue_wait", None)
            if queue_wait is not None:
                metric = r.histogram(
                    "sonata_queue_wait_seconds",
                    "Time requests spend in the batch-scheduler queue "
                    "before a device dispatch (or drop), per voice.",
                    buckets=queue_wait.bounds)
                metric.attach(queue_wait, **lbl)
                owned.append((metric, lbl))
        if replica_pool is not None:
            self._register_replica_pool(voice_id, replica_pool,
                                        labeled_gauge, voice_gauge)

    def _register_replica_pool(self, voice_id, pool, labeled_gauge,
                               voice_gauge) -> None:
        """Per-replica gauges + pool-level routing/breaker counters.

        Replica series carry a ``replica`` label next to ``voice``; the
        breaker state gauge is numeric (0 closed / 1 half-open / 2 open)
        so a dashboard can alert on ``> 0``.
        """
        r = self.registry
        owned = self._voice_series.setdefault(voice_id, [])
        for replica in pool.replicas:
            rl = {"voice": voice_id, "replica": str(replica.index)}

            def attr(r, name):
                return lambda: float(getattr(r, name))

            labeled_gauge("sonata_replica_outstanding",
                          "Requests routed to a replica and not yet "
                          "resolved.", attr(replica, "outstanding"), rl)
            labeled_gauge("sonata_replica_dispatches",
                          "Successful device dispatches, per replica.",
                          attr(replica, "dispatches"), rl)
            labeled_gauge("sonata_replica_dispatch_failures",
                          "Failed device dispatches, per replica.",
                          attr(replica, "dispatch_failures"), rl)
            # counter semantics via a scrape-time callback, like the rest
            # of the replica series: resubmissions used to be visible
            # only as the pool-level aggregate — this names the replica
            # whose failures pushed requests elsewhere
            resub = r.counter(
                "sonata_replica_resubmits_total",
                "Requests that failed on this replica and were "
                "resubmitted to another.")
            resub.labels(**rl).set_function(attr(replica, "resubmits"))
            owned.append((resub, rl))
            labeled_gauge("sonata_replica_breaker_state",
                          "Circuit breaker: 0 closed, 1 half-open, "
                          "2 open.", attr(replica, "state"), rl)
            labeled_gauge("sonata_replica_device",
                          "JAX device id this replica is pinned to.",
                          lambda r=replica: float(r.device_id), rl)

        def pool_stat(key):
            return lambda: float(pool.stats.get(key, 0))

        for key, help in (
                ("routed", "Requests routed into the replica pool"),
                ("resubmitted", "Requests resubmitted to another replica "
                                "after a replica fault"),
                ("failed", "Requests that failed out of the pool"),
                ("breaker_opens", "Circuit-breaker trips"),
                ("recovered", "Breakers closed again by a successful "
                              "trial")):
            voice_gauge(f"sonata_pool_{key}", f"{help}, per voice.",
                        pool_stat(key))
        voice_gauge("sonata_pool_healthy_replicas",
                    "Replicas currently accepting traffic, per voice.",
                    lambda: float(pool.healthy_count()))
        voice_gauge("sonata_pool_replicas",
                    "Total replicas in the pool, per voice.",
                    lambda: float(len(pool.replicas)))
        if self.scope is not None:
            probes = self._voice_probes.setdefault(voice_id, [])
            name = f"healthy_replicas:{voice_id}"
            self.scope.add_probe(name,
                                 lambda: float(pool.healthy_count()))
            probes.append(name)

    def unregister_voice(self, voice_id: str) -> None:
        """Drop a voice's labeled series after UnloadVoice — exactly the
        (metric, labels) pairs register_voice created (recorded per
        voice, so the two methods cannot drift apart), releasing the
        closures that would otherwise pin the unloaded voice's objects."""
        self.health.drop_voice(voice_id)
        for metric, labels in self._voice_series.pop(voice_id, []):
            metric.remove(**labels)
        for probe in self._voice_probes.pop(voice_id, []):
            if self.scope is not None:
                self.scope.remove_probe(probe)

    def close(self) -> None:
        degradation_mod.uninstall(self.degradation)
        if self.tenancy is not None:
            self.tenancy.close()
        if self.synth_cache is not None:
            self.synth_cache.close()
        if self.scope is not None:
            scope_mod.uninstall(self.scope)
            self.scope.close()
        if self.ledger is not None:
            self.ledger.close()
        if self.http is not None:
            self.http.stop()
            self.http = None
