"""Multi-device replica pool: route, batch, and fail over across chips.

A host with 4 or 8 accelerator chips serving through one
:class:`~sonata_tpu.synth.scheduler.BatchScheduler` uses exactly one chip
— the scheduler owns a single worker issuing ``speak_batch`` against
whatever device JAX picked by default — and a single device fault kills
the whole voice.  This module is the standard next step for an inference
stack (cf. Orca's iteration-level scheduling, OSDI '22; AlpaServe's
replica placement, OSDI '23): **replica-pool serving**.

- :class:`ReplicaPool` owns one :class:`Replica` per JAX local device
  (or a ``SONATA_REPLICAS=N`` prefix subset).  Each replica holds its
  own device-placed copy of the model (``jax.device_put`` of the params
  at pool construction pins every dispatch to that replica's chip — a
  committed operand places the whole XLA computation) and its own
  ``BatchScheduler``, so continuous batching happens *per chip*.
- The **router** submits each request to the healthy replica with the
  least outstanding work.  Deadlines and admission compose unchanged:
  the pool exposes the scheduler's ``submit/speak/queue_depth/stats``
  surface, so everything upstream (gRPC deadline propagation, admission
  shedding, metrics) works identically with or without a pool.
- **Fault isolation**: a replica whose device dispatches fail
  ``SONATA_REPLICA_BREAKER_THRESHOLD`` consecutive times (default 3) is
  circuit-broken — drained (its scheduler shut down; queued work fails
  out and is resubmitted), and every request that failed on it is
  resubmitted **exactly once** to a healthy replica, so a single sick
  chip degrades capacity instead of failing requests.  After
  ``SONATA_REPLICA_PROBE_INTERVAL_S`` (default 5 s) the breaker goes
  **half-open**: the router hands the replica one trial request; success
  closes the breaker, failure re-opens it with the probe interval
  **doubled** (plus jitter, capped at ``SONATA_REPLICA_PROBE_MAX_S``,
  default 60 s) — a persistently sick device is probed ever more
  rarely, not stormed.  Wedge-class faults (a dispatch stuck past the
  ``SONATA_DISPATCH_TIMEOUT_S`` watchdog, a crashed scheduler worker)
  trip the breaker immediately and recycle the replica's scheduler.
- **Health integration**: ``healthy_count()`` backs a readiness gate —
  a pool with zero healthy replicas flips ``/readyz`` (see
  :meth:`~sonata_tpu.serving.health.HealthState.add_readiness_gate`)
  so the load balancer routes around the whole host.

Everything is testable on CPU: ``XLA_FLAGS
=--xla_force_host_platform_device_count=4`` gives four independent host
devices, and the pool behaves identically (tests/test_replicas.py).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from concurrent.futures import CancelledError, Future
from typing import Callable, Optional, Sequence

from ..core import OperationError
from ..utils.profiling import QUEUE_WAIT_BUCKETS_S, Histogram
from . import degradation, faults, tracing
from .admission import Overloaded
from .deadlines import Deadline, DeadlineExceeded
from .drain import Draining

log = logging.getLogger("sonata.serving")

REPLICAS_ENV = "SONATA_REPLICAS"
BREAKER_THRESHOLD_ENV = "SONATA_REPLICA_BREAKER_THRESHOLD"
PROBE_INTERVAL_ENV = "SONATA_REPLICA_PROBE_INTERVAL_S"
#: cap for the exponential probe backoff: a replica whose trials keep
#: failing doubles its probe interval (plus jitter) up to this bound,
#: instead of probe-storming a persistently sick device every interval
PROBE_MAX_ENV = "SONATA_REPLICA_PROBE_MAX_S"
DEFAULT_BREAKER_THRESHOLD = 3
DEFAULT_PROBE_INTERVAL_S = 5.0
DEFAULT_PROBE_MAX_S = 60.0
#: fractional jitter on every probe delay, so a fleet of replicas (or
#: hosts) tripped by one event does not re-probe in lockstep
PROBE_JITTER = 0.1

# breaker states; exported as the numeric value of the
# sonata_replica_breaker_state gauge (0 = serving, 1 = probing, 2 = out)
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_replica_count() -> int:
    """``SONATA_REPLICAS`` parsed as a count: 0 when unset, non-positive,
    or garbage — the one place frontends ask "did the env turn the pool
    on?" (string truthiness would read the documented ``0 = off`` as
    on)."""
    return max(0, _env_int(REPLICAS_ENV, 0))


def resolve_replica_count(replicas: Optional[int] = None,
                          n_devices: Optional[int] = None) -> int:
    """How many replicas to run: explicit arg > ``SONATA_REPLICAS`` >
    one per local device; always clamped to [1, local device count]."""
    if n_devices is None:
        import jax

        n_devices = max(len(jax.local_devices()), 1)
    if replicas is None or replicas <= 0:
        replicas = _env_int(REPLICAS_ENV, 0)
    if replicas <= 0:
        replicas = n_devices
    return max(1, min(replicas, n_devices))


def resolve_replica_devices(replicas: Optional[int] = None) -> list:
    """The device prefix the pool will occupy (deterministic order, so
    two pools in one process stack onto the same chips predictably)."""
    import jax

    devices = list(jax.local_devices())
    return devices[:resolve_replica_count(replicas, len(devices))]


class _BreakerModel:
    """Model wrapper that reports dispatch outcomes to its replica.

    Failure counting must happen at *dispatch* granularity — K requests
    sharing one failed ``speak_batch`` are one fault, not K — so the
    breaker taps the model call itself rather than the per-request
    futures.  Everything else delegates to the wrapped model.
    """

    #: tells the scheduler this wrapper fires the dispatch failpoint
    #: itself, inside the failure accounting — an injected device fault
    #: must count toward the breaker exactly like a real one
    owns_dispatch_failpoint = True

    def __init__(self, model, replica: "Replica"):
        self._model = model
        self._replica = replica

    def speak_batch(self, sentences, *args, **kwargs):
        # capture the breaker generation BEFORE the call: a dispatch
        # thread the watchdog quarantined may complete arbitrarily late,
        # and its tap must not close a HALF_OPEN breaker (no trial ran)
        # or re-count a wedge the watchdog already accounted
        generation = self._replica.generation
        try:
            action = faults.fire("dispatch.device_call")
            out = faults.corrupt_result(
                action, self._model.speak_batch(sentences, *args, **kwargs))
        except Exception:
            self._replica._record_dispatch(ok=False, generation=generation)
            raise
        # a device answering the wrong number of rows is a DEVICE fault:
        # count it here, where the breaker can see it — the scheduler
        # fails the batch with the typed shape error downstream, after
        # this tap has run, and the pool resubmits off the sick replica
        ok = len(out) == len(sentences)
        self._replica._record_dispatch(ok=ok, generation=generation)
        return out

    # -- watchdog / crash hooks (called by the replica's scheduler) ----------
    def report_dispatch_stuck(self) -> None:
        """The watchdog convicted a dispatch that never returned: its
        breaker tap inside ``speak_batch`` runs only if the quarantined
        thread ever completes — and by then carries a stale generation
        and is ignored — so the scheduler reports the wedge here and the
        replica recycles now."""
        self._replica._report_fault("dispatch stuck past the watchdog")

    def report_scheduler_fault(self, exc: Exception) -> None:
        """The replica's scheduler worker crashed; recycle the replica so
        queued work resubmits and a probe rebuilds the scheduler."""
        self._replica._report_fault(f"scheduler worker crashed: {exc}")

    def __getattr__(self, name):
        return getattr(self._model, name)


class Replica:
    """One device's serving lane: model copy + scheduler + breaker."""

    def __init__(self, index: int, model, device=None,
                 scheduler_kwargs: Optional[dict] = None,
                 pool: "Optional[ReplicaPool]" = None):
        self.index = index
        self.device = device
        self.model = _BreakerModel(model, self)
        self._scheduler_kwargs = dict(scheduler_kwargs or {})
        if pool is not None:
            # one pool-shared queue-wait histogram: the per-voice metric
            # aggregates across replicas (and survives breaker-driven
            # scheduler recycling, which would reset a per-scheduler one)
            self._scheduler_kwargs.setdefault("queue_wait_hist",
                                              pool.queue_wait)
        attrs = {"replica": index}
        if pool is not None:
            # the pool is named after its voice (for_voice passes the
            # voice id): dispatch spans and the scope's padding-waste
            # accounting both key on it
            attrs["voice"] = pool.name
        if device is not None:
            attrs["device"] = str(device)
        self._scheduler_kwargs.setdefault("trace_attrs", attrs)
        self._pool = pool
        self.state = CLOSED
        self.consecutive_failures = 0
        self.dispatches = 0        # successful device dispatches
        self.dispatch_failures = 0  # failed device dispatches
        self.submitted = 0         # requests routed here (lifetime)
        self.outstanding = 0       # routed, not yet resolved
        self.resubmits = 0         # requests that failed here and were
        #                            retried on another replica
        self.opened_at: Optional[float] = None
        self.next_probe_at: Optional[float] = None
        #: current probe backoff (seconds, pre-jitter): reset to the pool
        #: base on a fresh trip, doubled (capped) on every failed trial,
        #: cleared when the breaker closes
        self.probe_backoff_s: Optional[float] = None
        #: breaker generation, bumped on every trip: dispatches started
        #: before a trip (e.g. a watchdog-quarantined thread finishing
        #: late) carry a stale generation and their breaker tap is
        #: ignored — the trip already accounted them
        self.generation = 0
        self.scheduler = self._new_scheduler()

    def _new_scheduler(self):
        from ..synth.scheduler import BatchScheduler

        return BatchScheduler(self.model, **self._scheduler_kwargs)

    @property
    def device_id(self) -> int:
        return getattr(self.device, "id", self.index)

    def _record_dispatch(self, *, ok: bool,
                         generation: Optional[int] = None) -> None:
        pool = self._pool
        if pool is not None:
            pool._on_dispatch(self, ok, generation=generation)

    def _report_fault(self, reason: str) -> None:
        """A wedge-class fault (stuck dispatch, crashed worker): recycle
        immediately — the scheduler/thread state is unusable regardless
        of how many consecutive failures came before."""
        pool = self._pool
        if pool is not None:
            pool._recycle_replica(self, reason)

    def snapshot(self) -> dict:
        return {"index": self.index, "device": str(self.device),
                "state": _STATE_NAMES[self.state],
                "outstanding": self.outstanding,
                "submitted": self.submitted,
                "dispatches": self.dispatches,
                "dispatch_failures": self.dispatch_failures,
                "resubmits": self.resubmits,
                "probe_backoff_s": self.probe_backoff_s,
                "queue_depth": self.scheduler.queue_depth()}


class ReplicaPool:
    """Route requests across per-device replicas with fault isolation.

    Duck-type-compatible with :class:`BatchScheduler` (``submit`` /
    ``speak`` / ``queue_depth`` / ``stats`` / ``stats_view`` /
    ``shutdown``), so frontends swap a pool in wherever a scheduler went.
    """

    def __init__(self, models: Sequence, devices: Optional[Sequence] = None,
                 *, breaker_threshold: Optional[int] = None,
                 probe_interval_s: Optional[float] = None,
                 probe_max_s: Optional[float] = None,
                 scheduler_kwargs: Optional[dict] = None,
                 on_health_change: Optional[Callable[[int], None]] = None,
                 name: str = "pool"):
        if not models:
            raise OperationError("a replica pool needs at least one model")
        if devices is not None and len(devices) != len(models):
            raise OperationError(
                f"{len(models)} models for {len(devices)} devices")
        self.name = name
        self.breaker_threshold = max(1, (
            breaker_threshold if breaker_threshold is not None
            else _env_int(BREAKER_THRESHOLD_ENV, DEFAULT_BREAKER_THRESHOLD)))
        self.probe_interval_s = max(0.01, (
            probe_interval_s if probe_interval_s is not None
            else _env_float(PROBE_INTERVAL_ENV, DEFAULT_PROBE_INTERVAL_S)))
        # never below the base: a pinned-long base interval (the CI
        # smoke's 600 s) must not be clipped by the default cap
        self.probe_max_s = max(self.probe_interval_s, (
            probe_max_s if probe_max_s is not None
            else _env_float(PROBE_MAX_ENV, DEFAULT_PROBE_MAX_S)))
        self._lock = threading.RLock()
        self._closed = False
        #: drain state (terminal, always followed by shutdown): the pool
        #: refuses new submits, breaker resubmission, and half-open
        #: probe rebuilds FAST and TYPED instead of racing the teardown
        self._draining = False
        self._on_health_change = on_health_change
        #: pool-level counters (replica-level ones live on each Replica)
        self.stats = {"routed": 0, "resubmitted": 0, "failed": 0,
                      "breaker_opens": 0, "recovered": 0}
        #: shared across every replica's scheduler (see Replica.__init__)
        self.queue_wait = Histogram(QUEUE_WAIT_BUCKETS_S)
        self.replicas = [
            Replica(i, m, device=(devices[i] if devices else None),
                    scheduler_kwargs=scheduler_kwargs, pool=self)
            for i, m in enumerate(models)]
        self._probe_wake = threading.Event()
        self._prober = threading.Thread(target=self._probe_loop,
                                        name="sonata_replica_probe",
                                        daemon=True)
        self._prober.start()

    # -- construction ---------------------------------------------------------
    @classmethod
    def for_voice(cls, voice, replicas: Optional[int] = None,
                  **kwargs) -> "ReplicaPool":
        """One replica per local device (or the ``SONATA_REPLICAS`` /
        ``replicas`` prefix), each with the voice's params
        ``jax.device_put`` onto its chip."""
        devices = resolve_replica_devices(replicas)
        models = [voice.replica_for_device(d, seed_offset=i)
                  for i, d in enumerate(devices)]
        return cls(models, devices, **kwargs)

    # -- scheduler-compatible surface ----------------------------------------
    def submit(self, phonemes: str, speaker: Optional[int] = None,
               scales=None,
               deadline: Optional[Deadline] = None) -> "Future":
        """Route one request to the least-loaded healthy replica.

        Returns a pool-level future.  A dispatch-level failure on the
        chosen replica resubmits the request exactly once to a different
        healthy replica before the client sees an error; request-level
        errors (bad speaker, expired deadline, full queue) propagate
        unchanged — they would fail identically anywhere.
        """
        if self._closed:
            raise OperationError("replica pool is shut down")
        if self._draining:
            raise Draining(
                f"draining: replica pool {self.name!r} is shutting down "
                "for a restart; not accepting new work")
        outer: "Future" = Future()
        with self._lock:
            self.stats["routed"] += 1
        # captured here, on the request thread: the resubmit path runs on
        # a future-callback thread where the ambient context is gone, yet
        # its spans must land in THIS request's trace
        self._route(outer, phonemes, speaker, scales, deadline,
                    resubmits_left=1, exclude=(),
                    tctx=tracing.current(), t_first=time.monotonic())
        return outer

    def speak(self, phonemes: str, timeout: Optional[float] = None,
              speaker: Optional[int] = None, scales=None,
              deadline: Optional[Deadline] = None):
        return self.submit(phonemes, speaker=speaker, scales=scales,
                           deadline=deadline).result(timeout)

    def speak_many(self, phoneme_list: Sequence[str], *, speaker=None,
                   scales=None, deadline: Optional[Deadline] = None,
                   timeout: Optional[float] = None) -> list:
        """Submit a batch of sentences across the pool and gather results
        in order (the CLI's / batched stream's fan-out)."""
        futures = [self.submit(p, speaker=speaker, scales=scales,
                               deadline=deadline) for p in phoneme_list]
        return [f.result(timeout) for f in futures]

    def warmup(self, phoneme_list: Sequence[str]) -> None:
        """Run the given sentences through EVERY healthy replica (not the
        router) and wait.  Readiness warmup must compile each chip's
        executables — routed traffic would warm only the least-loaded
        replica and leave the rest to pay cold XLA compiles under real
        load."""
        futures = [r.scheduler.submit(p)
                   for r in self.replicas if r.state == CLOSED
                   for p in phoneme_list]
        for fut in futures:
            fut.result()

    def queue_depth(self) -> int:
        return sum(r.scheduler.queue_depth() for r in self.replicas)

    def set_dispatch_timeout(self, seconds: Optional[float]) -> None:
        """(Re)arm the hung-dispatch watchdog on every replica's
        scheduler, including ones the probe loop rebuilds later (the
        kwarg is recorded so ``_new_scheduler`` inherits it).  None
        means *disable*, so it is recorded as 0.0 — a raw None kwarg
        would make a rebuilt scheduler fall back to the env value and
        silently resurrect a watchdog the operator turned off.

        Runs under the pool lock, and replaces the kwargs dict wholesale
        rather than mutating it: ``_new_scheduler`` unpacks the dict
        OUTSIDE the lock in the probe loop, so an in-place first-time
        key insert could resize it mid-unpack.  A rebuild racing this
        call may still have snapshotted the old kwargs — the probe loop
        re-applies the recorded value at install time to close that."""
        resolved = seconds if seconds is not None else 0.0
        with self._lock:
            for r in self.replicas:
                r._scheduler_kwargs = dict(r._scheduler_kwargs,
                                           dispatch_timeout_s=resolved)
                # a plain attribute store on the scheduler: safe (and
                # race-free with the rebuild install) under the lock
                r.scheduler.set_dispatch_timeout(resolved)

    def stats_view(self) -> dict:
        """Aggregate scheduler stats across replicas plus the pool's own
        routing/breaker counters — same keys a lone ``BatchScheduler``
        exposes, so log lines and benches read either transparently."""
        agg = {"requests": 0, "dispatches": 0, "shed": 0, "expired": 0,
               "cancelled": 0, "stuck": 0}
        for r in self.replicas:
            for k, v in r.scheduler.stats_view().items():
                if k in agg:
                    agg[k] += v
        agg["coalescing_ratio"] = round(
            agg["requests"] / max(agg["dispatches"], 1), 3)
        with self._lock:
            agg.update(self.stats)
            agg["replicas"] = len(self.replicas)
            agg["healthy_replicas"] = self._healthy_count_locked()
        return agg

    def start_draining(self) -> None:
        """Enter the drain state ahead of :meth:`shutdown` (the frontend
        calls this once its in-flight wait is over, just before voice
        teardown).  From here on: new submits, breaker resubmission, and
        half-open probe rebuilds all refuse fast with a typed
        :class:`~sonata_tpu.serving.drain.Draining` — a breaker trip
        racing the teardown must not feed work into a closing scheduler,
        and a probe must not build a worker thread nobody will join.
        Queued and in-flight dispatches are untouched; they finish (or
        fail out) through their schedulers as usual."""
        with self._lock:
            if self._draining or self._closed:
                return
            self._draining = True
        log.info("pool %s: draining (no new submits, no resubmission, "
                 "no probe rebuilds)", self.name)
        self._probe_wake.set()  # the prober exits instead of rebuilding

    @property
    def draining(self) -> bool:
        return self._draining

    def shutdown(self) -> None:
        """Drain the whole pool: every replica's scheduler shuts down and
        fails its queued work (no resubmission — the pool is closing)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._draining = True
        self._probe_wake.set()
        for r in self.replicas:
            r.scheduler.shutdown()
        self._prober.join(timeout=5.0)

    # -- health ---------------------------------------------------------------
    def _healthy_count_locked(self) -> int:
        return sum(1 for r in self.replicas if r.state != OPEN)

    def healthy_count(self) -> int:
        """Replicas currently accepting traffic (closed or probing)."""
        with self._lock:
            return self._healthy_count_locked()

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "closed": self._closed,
                    "draining": self._draining,
                    "healthy": self._healthy_count_locked(),
                    "stats": dict(self.stats),
                    "replicas": [r.snapshot() for r in self.replicas]}

    def _notify_health(self) -> None:
        cb = self._on_health_change
        if cb is not None:
            try:
                cb(self.healthy_count())
            except Exception:
                log.exception("replica-pool health callback failed")

    # -- routing --------------------------------------------------------------
    def _pick(self, exclude: tuple) -> Replica:
        with self._lock:
            # a half-open replica with nothing in flight gets the next
            # request as its trial — that's how the breaker closes again
            for r in self.replicas:
                if (r.state == HALF_OPEN and r.outstanding == 0
                        and r not in exclude):
                    r.outstanding += 1
                    r.submitted += 1
                    return r
            closed = [r for r in self.replicas
                      if r.state == CLOSED and r not in exclude]
            if not closed:
                raise Overloaded(
                    f"replica pool {self.name!r}: no healthy replica "
                    f"available ({self._healthy_count_locked()} of "
                    f"{len(self.replicas)} non-open)")
            best = min(closed, key=lambda r: r.outstanding)
            best.outstanding += 1
            best.submitted += 1
            return best

    def _release(self, replica: Replica) -> None:
        with self._lock:
            if replica.outstanding > 0:
                replica.outstanding -= 1

    def _route(self, outer: "Future", phonemes, speaker, scales, deadline,
               *, resubmits_left: int, exclude: tuple,
               tctx=None, t_first: Optional[float] = None) -> None:
        tried = list(exclude)
        try:
            faults.fire("pool.route")
        except OperationError as e:
            # an injected routing fault fails the request like any other
            # pool-level refusal (never crashes a resubmit callback)
            self._fail(outer, e)
            return
        while True:
            try:
                replica = self._pick(tuple(tried))
            except Overloaded as e:
                degradation.note_shed()  # capacity shed: no healthy replica
                self._fail(outer, e)
                return
            try:
                inner = replica.scheduler.submit(
                    phonemes, speaker=speaker, scales=scales,
                    deadline=deadline, trace_ctx=tctx)
            except (Overloaded, DeadlineExceeded) as e:
                # request-level refusal: a full per-replica queue or an
                # already-dead deadline would refuse anywhere — surface it
                self._release(replica)
                self._fail(outer, e)
                return
            except OperationError as e:
                self._release(replica)
                if ("shut down" in str(e) and not self._closed
                        and not self._draining):
                    # raced a concurrent breaker-open drain on this
                    # replica: no dispatch happened, so retrying another
                    # replica does not spend the resubmit budget
                    tried.append(replica)
                    continue
                if self._draining:
                    # the teardown is what closed the scheduler under
                    # us: surface the drain, not the raced internals
                    self._fail(outer, Draining(
                        f"draining: replica pool {self.name!r} is "
                        f"shutting down ({type(e).__name__}: {e})"))
                    return
                self._fail(outer, e)
                return
            break
        inner.add_done_callback(
            lambda fut, r=replica: self._on_done(
                outer, fut, r, phonemes, speaker, scales, deadline,
                resubmits_left, tctx, t_first))

    def _on_done(self, outer: "Future", inner: "Future", replica: Replica,
                 phonemes, speaker, scales, deadline,
                 resubmits_left: int, tctx=None,
                 t_first: Optional[float] = None) -> None:
        self._release(replica)
        try:
            result = inner.result()
        except CancelledError:
            outer.cancel()
            return
        except (DeadlineExceeded, Overloaded) as e:
            self._fail(outer, e)  # the request's own fault, not the chip's
            return
        except Exception as e:
            # replica-fault path (device dispatch error, or the replica
            # was drained under us): fail over — once
            if self._draining:
                # drain-vs-resubmission race class: a breaker trip while
                # the pool is draining must NOT resubmit into a closing
                # scheduler — fail fast and typed so the client (and the
                # ladder) sees a deploy, not a fault or overload
                self._fail(outer, Draining(
                    f"draining: replica pool {self.name!r} is shutting "
                    f"down; not resubmitting after "
                    f"{type(e).__name__}: {e}"))
                return
            if (resubmits_left > 0 and not self._closed
                    and (deadline is None or deadline.alive())):
                now = time.monotonic()
                added_ms = (round((now - t_first) * 1e3, 3)
                            if t_first is not None else None)
                with self._lock:
                    self.stats["resubmitted"] += 1
                    replica.resubmits += 1
                hop = 1 + (1 - resubmits_left)  # 1 resubmit budget today
                request_id = tctx[0].request_id if tctx else None
                if tctx is not None:
                    # make the failover visible to the request itself:
                    # without this span the retried request's trace shows
                    # a clean dispatch and silently absorbs the latency
                    trace, parent = tctx
                    trace.new_span(
                        "resubmit", parent=parent, start=now, end=now,
                        attrs={"failed_replica": replica.index,
                               "retry_hop": hop,
                               "latency_before_retry_ms": added_ms,
                               "error": f"{type(e).__name__}: {e}"})
                log.warning(
                    "pool %s: resubmitting request off replica %d "
                    "(hop %d, %.1f ms already spent: %s)", self.name,
                    replica.index, hop, added_ms or 0.0, e,
                    extra={"replica": replica.index,
                           "request_id": request_id})
                self._route(outer, phonemes, speaker, scales, deadline,
                            resubmits_left=resubmits_left - 1,
                            exclude=(replica,), tctx=tctx, t_first=t_first)
                return
            self._fail(outer, e)
            return
        try:
            outer.set_result(result)
        except Exception:
            pass  # outer was cancelled; tolerated like the scheduler does

    def _fail(self, outer: "Future", exc: Exception) -> None:
        with self._lock:
            self.stats["failed"] += 1
        try:
            outer.set_exception(exc)
        except Exception:
            pass

    # -- breaker --------------------------------------------------------------
    def _open_locked(self, replica: Replica, *, failed_trial: bool) -> None:
        """Flip a replica OPEN and schedule its next probe (pool lock
        held).  Backoff: a fresh trip probes after the base interval; a
        failed half-open trial doubles the interval up to
        ``probe_max_s`` — plus jitter — so a persistently sick device is
        probed ever more rarely instead of stormed."""
        replica.state = OPEN
        replica.opened_at = time.monotonic()
        replica.generation += 1  # in-flight dispatches are now stale
        if failed_trial and replica.probe_backoff_s is not None:
            replica.probe_backoff_s = min(replica.probe_backoff_s * 2,
                                          self.probe_max_s)
        else:
            replica.probe_backoff_s = self.probe_interval_s
        replica.next_probe_at = (replica.opened_at
                                 + self._jittered(replica.probe_backoff_s))
        self.stats["breaker_opens"] += 1

    @staticmethod
    def _jittered(seconds: float) -> float:
        return seconds * (1.0 + PROBE_JITTER * random.random())

    def _drain_off_thread(self, scheduler, index: int) -> None:
        """Shut a scheduler down on a helper thread: ``shutdown()`` joins
        the scheduler's worker — which may be the very thread running the
        breaker callback — and must never run under the pool lock."""
        threading.Thread(target=scheduler.shutdown,
                         name=f"sonata_replica_drain_{index}",
                         daemon=True).start()

    def _on_dispatch(self, replica: Replica, ok: bool,
                     generation: Optional[int] = None) -> None:
        """Dispatch-granular breaker bookkeeping (called by the
        replica's :class:`_BreakerModel` around every ``speak_batch``)."""
        to_drain = None
        with self._lock:
            if (generation is not None
                    and generation != replica.generation):
                # a dispatch from before a breaker trip finishing late —
                # a watchdog-quarantined thread, typically.  The trip
                # already accounted it: a late success must not close a
                # HALF_OPEN breaker (no trial ran), a late failure must
                # not double-count the wedge.
                log.info("pool %s: replica %d ignoring stale dispatch "
                         "result (generation %d != %d)", self.name,
                         replica.index, generation, replica.generation)
                return
            if ok:
                replica.dispatches += 1
                replica.consecutive_failures = 0
                if replica.state == HALF_OPEN:
                    replica.state = CLOSED
                    replica.probe_backoff_s = None  # backoff resets
                    self.stats["recovered"] += 1
                    log.info("pool %s: replica %d trial dispatch "
                             "succeeded; breaker closed", self.name,
                             replica.index)
                    notify = True
                else:
                    notify = False
            else:
                replica.dispatch_failures += 1
                replica.consecutive_failures += 1
                failed_trial = replica.state == HALF_OPEN
                trip = (failed_trial
                        or (replica.state == CLOSED
                            and replica.consecutive_failures
                            >= self.breaker_threshold))
                notify = trip
                if trip:
                    self._open_locked(replica, failed_trial=failed_trial)
                    to_drain = replica.scheduler
                    log.error(
                        "pool %s: replica %d circuit-broken after %d "
                        "consecutive dispatch failures; draining "
                        "(next probe in %.1fs)", self.name, replica.index,
                        replica.consecutive_failures,
                        replica.probe_backoff_s)
        if to_drain is not None:
            # drain off-thread: shutdown() joins the scheduler worker —
            # the very thread this callback may be running on
            self._drain_off_thread(to_drain, replica.index)
            self._probe_wake.set()  # re-arm the prober's timer
        if notify:
            self._notify_health()

    def force_open(self, index: int, reason: str = "operator") -> None:
        """Trip one replica's breaker by hand (ops escape hatch; also
        what the CI smoke uses to prove readiness survives a dead chip)."""
        with self._lock:
            replica = self.replicas[index]
            if replica.state == OPEN:
                return
            self._open_locked(replica, failed_trial=False)
            sched = replica.scheduler
        log.warning("pool %s: replica %d force-opened (%s)", self.name,
                    index, reason)
        self._drain_off_thread(sched, index)
        self._probe_wake.set()
        self._notify_health()

    def _recycle_replica(self, replica: Replica, reason: str) -> None:
        """Immediate trip for wedge-class faults (stuck dispatch, crashed
        scheduler worker): the replica's scheduler state is unusable, so
        it drains now — queued work fails out and resubmits — and the
        probe loop rebuilds a fresh scheduler for the half-open trial.
        Runs on the replica's own scheduler worker thread, so the drain
        must (and does) happen off-thread."""
        with self._lock:
            if replica.state == OPEN:
                # the trip that opened the breaker already accounted the
                # wedge — a second conviction racing the drain must not
                # re-count it (mirrors _on_dispatch's generation guard)
                return
            replica.dispatch_failures += 1
            replica.consecutive_failures += 1
            self._open_locked(replica,
                              failed_trial=replica.state == HALF_OPEN)
            sched = replica.scheduler
        log.error("pool %s: replica %d recycling (%s); draining and "
                  "rebuilding (next probe in %.1fs)", self.name,
                  replica.index, reason, replica.probe_backoff_s)
        self._drain_off_thread(sched, replica.index)
        self._probe_wake.set()
        self._notify_health()

    def _probe_loop(self) -> None:
        """Flip OPEN replicas to HALF_OPEN once their probe time comes;
        the router then hands each exactly one trial request."""
        while not self._closed:
            if self._draining:
                # a draining pool never comes back from OPEN: building a
                # fresh scheduler now would orphan its worker thread in
                # the teardown (the drain-vs-probe race class).  The
                # drain is terminal, so the prober simply exits.
                log.info("pool %s: probe loop exiting (pool draining)",
                         self.name)
                return
            with self._lock:
                due = [r for r in self.replicas
                       if r.state == OPEN and r.next_probe_at is not None]
                now = time.monotonic()
                wait = min((r.next_probe_at - now for r in due),
                           default=self.probe_interval_s)
            if wait > 0:
                self._probe_wake.wait(timeout=wait)
                self._probe_wake.clear()
                continue
            with self._lock:
                if self._closed or self._draining:
                    # shutdown()/start_draining() may have raced the
                    # wait above — installing a fresh scheduler now
                    # would leak its worker thread
                    return
                now = time.monotonic()
                ripe = []
                for r in self.replicas:
                    if (r.state == OPEN and r.next_probe_at is not None
                            and now >= r.next_probe_at):
                        # Push the next probe out now (at the replica's
                        # current backoff), so a trial that fails before
                        # its own _on_dispatch runs cannot re-probe in a
                        # tight loop.
                        r.next_probe_at = now + self._jittered(
                            r.probe_backoff_s or self.probe_interval_s)
                        ripe.append(r)
            # Fresh schedulers are built OUTSIDE the pool lock: scheduler
            # construction resolves the model's dispatch policy, which may
            # run a device probe (seconds on a cold backend) — holding the
            # lock here would stall routing/breaker bookkeeping on every
            # OTHER healthy replica for the duration (sonata-lint
            # lock-order pass; pinned by
            # test_replicas.test_probe_rebuild_does_not_hold_pool_lock).
            # Construction against a still-sick device can itself raise
            # (that same dispatch-policy probe): a failed build must not
            # kill this thread — it is the pool's ONLY path back from
            # OPEN — so the replica stays OPEN and retries at its next
            # (already backed-off) probe.
            fresh = []
            for r in ripe:
                try:
                    fresh.append((r, r._new_scheduler()))
                except Exception:
                    log.exception(
                        "pool %s: replica %d scheduler rebuild failed; "
                        "retrying at next probe", self.name, r.index)
                    with self._lock:
                        if r.state == OPEN:
                            r.probe_backoff_s = min(
                                (r.probe_backoff_s or
                                 self.probe_interval_s) * 2,
                                self.probe_max_s)
                            r.next_probe_at = (time.monotonic()
                                               + self._jittered(
                                                   r.probe_backoff_s))
            changed = False
            with self._lock:
                for r, sched in fresh:
                    if self._closed or self._draining or r.state != OPEN:
                        # raced shutdown()/start_draining() (or an
                        # operator state change): installing now would
                        # leak the worker thread
                        self._drain_off_thread(sched, r.index)
                        continue
                    # the old scheduler was drained at trip time
                    r.consecutive_failures = 0
                    # re-apply the recorded watchdog bound: this build's
                    # kwargs snapshot may predate a set_dispatch_timeout
                    # that ran while construction was off-lock
                    timeout = r._scheduler_kwargs.get("dispatch_timeout_s")
                    if timeout is not None:
                        sched.set_dispatch_timeout(timeout)
                    r.scheduler = sched
                    r.state = HALF_OPEN
                    changed = True
                    log.info("pool %s: replica %d half-open; next "
                             "request is its trial", self.name, r.index)
                closed = self._closed
            if changed:
                self._notify_health()
            if closed:
                return
