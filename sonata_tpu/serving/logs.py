"""Structured logging for the serving frontends.

Two things the bare ``logging.basicConfig`` the frontends used could not
do:

- **Request correlation**: every record carries the active request's
  ``request_id`` (and its ``voice``), injected by
  :class:`TraceContextFilter` from the request trace the frontend opened
  (:mod:`.tracing`) — no call site has to remember to pass it.  Records
  emitted with ``extra={"request_id": ..., "replica": ...}`` (e.g. the
  replica pool's resubmission warning, which runs on a callback thread
  where the trace context is gone) keep their explicit values.
- **Machine-readable lines**: ``--log-format json`` (or
  ``SONATA_LOG_FORMAT=json``) switches to one JSON object per line —
  ``{"ts", "level", "logger", "message", "request_id"?, "voice"?,
  "replica"?, "degradation"?, "slo_breach"?}`` — which is what a log
  pipeline joins against the trace export from ``SONATA_TRACE_LOG``
  and the flight-recorder timeline (``/debug/timeline``): every line
  carries the degradation-ladder level at emit time, and ``slo_breach``
  appears whenever an SLO's fast-window burn rate exceeded 1.0 at the
  scope's last tick.

The text format stays the familiar ``asctime name level message``, with
`` rid=<request_id>`` appended whenever one is known — plus
`` lvl=<level>`` / `` slo_breach`` only while the process is degraded
or breaching — so grepping a request (or an incident) across the
server log works in either mode.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Optional

from . import degradation, scope, tracing

LOG_FORMAT_ENV = "SONATA_LOG_FORMAT"

#: fields TraceContextFilter injects / JsonLineFormatter surfaces
_CONTEXT_FIELDS = ("request_id", "voice", "replica", "degradation",
                   "slo_breach")


class TraceContextFilter(logging.Filter):
    """Attach the active trace's request_id/voice — plus the process
    health context (degradation level, SLO-breach flag) — to every
    record.

    Explicit ``extra=`` values win; records logged outside any request
    context get ``None`` (rendered as absent).  ``degradation`` is the
    ladder level at emit time (present whenever a ladder is installed,
    0 included, so log lines join against the flight-recorder
    timeline); ``slo_breach`` appears — as ``True`` — only while some
    SLO's fast-window burn exceeds 1.0."""

    def filter(self, record: logging.LogRecord) -> bool:
        trace = tracing.current_trace()
        if getattr(record, "request_id", None) is None:
            record.request_id = trace.request_id if trace else None
        if getattr(record, "voice", None) is None:
            record.voice = trace.attrs.get("voice") if trace else None
        if getattr(record, "replica", None) is None:
            record.replica = None
        if getattr(record, "degradation", None) is None:
            ladder = degradation.installed()
            record.degradation = (ladder.current_level()
                                  if ladder is not None else None)
        if getattr(record, "slo_breach", None) is None:
            sc = scope.installed()
            # cached at the scope's 1 Hz tick: an attribute read here,
            # never burn-rate math per log record
            record.slo_breach = True if (sc is not None
                                         and sc.slo_breach) else None
        return True


class JsonLineFormatter(logging.Formatter):
    """One JSON object per line; context fields included when present."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.localtime(record.created))
                  + f".{int(record.msecs):03d}",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        for field in _CONTEXT_FIELDS:
            value = getattr(record, field, None)
            if value is not None and value != "":
                entry[field] = value
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, ensure_ascii=False)


class TextFormatter(logging.Formatter):
    """The classic line format plus `` rid=<id>`` when a request is
    known — and, only while the process is degraded or breaching an
    SLO, `` lvl=<n>`` / `` slo_breach`` (healthy lines stay clean)."""

    def __init__(self):
        super().__init__("%(asctime)s %(name)s %(levelname)s %(message)s")

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        rid = getattr(record, "request_id", None)
        if rid:
            line += f" rid={rid}"
        level = getattr(record, "degradation", None)
        if level:
            line += f" lvl={level}"
        if getattr(record, "slo_breach", None):
            line += " slo_breach"
        return line


def configure_logging(level: Optional[str] = None,
                      fmt: Optional[str] = None, *,
                      env_level_var: str = "SONATA_LOG",
                      stream=None) -> None:
    """Install the serving log pipeline on the root logger.

    Precedence: explicit args (the ``--log-level`` / ``--log-format``
    flags) > env (``env_level_var`` for level — ``SONATA_GRPC`` for the
    server, ``SONATA_LOG`` for the CLI, both preserved from the
    reference — and ``SONATA_LOG_FORMAT``) > defaults (INFO, text).
    Replaces existing root handlers, so it is safe to call once at each
    frontend's entry point.
    """
    level_name = (level or os.environ.get(env_level_var) or "INFO").upper()
    resolved = getattr(logging, level_name, None)
    if not isinstance(resolved, int):
        resolved = logging.INFO
    fmt = (fmt or os.environ.get(LOG_FORMAT_ENV) or "text").lower()
    handler = logging.StreamHandler(stream if stream is not None
                                    else sys.stderr)
    handler.addFilter(TraceContextFilter())
    handler.setFormatter(JsonLineFormatter() if fmt == "json"
                         else TextFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(resolved)
