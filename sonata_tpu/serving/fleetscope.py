"""sonata-fleetscope: the fleet-aggregated observability plane.

PR 12 federated N sonata servers behind the mesh router, but every
observability surface PR 7 built — stage quantiles, SLO burn, waste
tables, the flight recorder — stops at the process boundary: an
operator of a 10-node fleet has 10 ``/debug/quantiles`` pages and no
answer to "what is fleet-wide TTFB p99, which node is the outlier, and
what was the whole fleet doing when the breaker tripped?".  The PR-7
sketches were built *mergeable* (merge == union, pinned) precisely so
aggregation could cross hosts; this module closes that loop on the
router, in four coupled pieces:

1. **Scope-export scraping.**  Each node serves its whole aggregation
   plane as a compact versioned payload (bins + slot epochs, never
   samples) at ``GET /debug/scope/export``; the mesh prober calls
   :meth:`FleetScope.on_probe_cycle` every health cycle and this module
   pulls the export on its own slower cadence
   (``SONATA_FLEET_SCRAPE_INTERVAL_S``, default 5 s).  A version
   mismatch is rejected loud and typed per node
   (:class:`~.sketches.SketchImportError`) — never folded.  Staleness
   past ``SONATA_FLEET_SCRAPE_STALE_S`` evicts the node to unroutable:
   a node whose observability plane is wedged must not keep looking
   healthy just because the last good scrape said so.
2. **Fleet aggregation.**  Node sketch exports merge into fleet-wide
   per-stage quantiles (bucket union == pooling the raw observations,
   so the 1% relative-error guarantee survives the hop — pinned across
   real processes in tests/test_fleetscope.py), fleet SLO burn rates
   (same ``SONATA_SLO`` grammar, fast/slow windows), and
   per-node-vs-fleet deltas that name outlier nodes.  Exported as
   ``sonata_fleet_stage_quantile{stage,q,window}``,
   ``sonata_fleet_slo_burn_rate{slo,window}``,
   ``sonata_fleet_node_delta{node_id,stage}``,
   ``sonata_mesh_node_scrape_age_seconds{node_id}``, and the
   ``GET /debug/fleet`` JSON scoreboard (per-node health, occupancy,
   scrape staleness, burn, top waste buckets).
3. **Stitched distributed traces.**  ``GET /debug/traces/stitched?id=``
   finds the router's own trace for a request id, learns the serving
   node from its ``mesh-dispatch`` span, fetches that node's trace over
   ``/debug/traces?id=``, re-bases the node's clock through the
   scrape-measured wall offset, and splices both span trees into one
   Chrome-trace document — one Perfetto load shows the whole cross-host
   request (router admission → mesh-dispatch → stream-emit, reroutes
   included, over the node's queue → dispatch → decode).
4. **Fleet flight recorder.**  A 1 Hz ring of fleet snapshots
   (per-node routable/breaker/outstanding/scrape-age plus fleet
   rollups and fast burn), auto-dumped to ``SONATA_FLEET_DUMP_DIR``
   (falling back to ``SONATA_TIMELINE_DUMP_DIR``) on node eviction,
   breaker trip, or a fleet-level fast-burn breach — reusing the PR-7
   per-reason rate limiting so a flapping breaker cannot starve a burn
   incident of its dump.

Cost model: scraping is one small HTTP GET per node per cadence on the
node's existing debug plane (node-side cost measured ≤ the PR-7 2%
bar, FLEET_r01.json); aggregation work happens router-side at scrape
and query time, never on the audio hot path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.parse
from collections import deque
from typing import Callable, Dict, List, Optional

from . import sketches, tracing
from .mesh import _http_fetch
from .scope import (
    DUMP_DIR_ENV,
    DUMP_MIN_INTERVAL_S,
    FAST_WINDOW,
    QUANTILES,
    SLOW_WINDOW,
    STAGES,
    WINDOWS,
    parse_slos,
)
from .sketches import QuantileSketch, SketchImportError

log = logging.getLogger("sonata.serving")

FLEET_SCRAPE_INTERVAL_ENV = "SONATA_FLEET_SCRAPE_INTERVAL_S"
FLEET_SCRAPE_STALE_ENV = "SONATA_FLEET_SCRAPE_STALE_S"
FLEET_RECORDER_CAP_ENV = "SONATA_FLEET_RECORDER_CAP"
FLEET_DUMP_DIR_ENV = "SONATA_FLEET_DUMP_DIR"

DEFAULT_SCRAPE_INTERVAL_S = 5.0
DEFAULT_SCRAPE_STALE_S = 30.0
DEFAULT_RECORDER_CAP = 600
DEFAULT_TICK_INTERVAL_S = 1.0

#: the outlier lens: per-node-vs-fleet deltas compare this quantile
#: over this window (positive delta = the node is slower than the
#: fleet merge at its tail)
DELTA_WINDOW = FAST_WINDOW[0]
DELTA_QUANTILE = 0.99

#: window seconds by label (age-expiry at merge time needs them)
_WINDOW_SECONDS = {label: seconds for label, seconds, _slots in WINDOWS}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except ValueError:
        return default


#: fleet-level metric families, loop-registered like the scope's
#: GAUGE_FAMILIES so the sonata-lint metricsdoc pass resolves the names
FLEET_GAUGE_FAMILIES = (
    ("sonata_fleet_stage_quantile",
     "Fleet-wide rolling per-stage latency quantile in seconds, merged "
     "from every reporting node's sketch export, by stage, quantile "
     "(p50/p90/p99) and window (1m/5m/1h)."),
    ("sonata_fleet_slo_burn_rate",
     "Fleet-wide SLO burn rate by objective and window (node SLO "
     "counters summed; 1.0 = the whole fleet consuming exactly its "
     "error budget)."),
    ("sonata_fleet_nodes_reporting",
     "Backend nodes whose scope export has been imported (the fleet "
     "quantiles' population)."),
)

#: per-node labeled families (series appear once a node's export has
#: taught the router its node_id, removed on close)
FLEET_NODE_GAUGE_FAMILIES = (
    ("sonata_fleet_node_delta",
     "Per-node minus fleet-merged 5m p99 in seconds, by node_id and "
     "stage (positive = this node is slower than the fleet — the "
     "outlier finder)."),
    ("sonata_mesh_node_scrape_age_seconds",
     "Seconds since this node's scope export last scraped OK, by "
     "node_id; past SONATA_FLEET_SCRAPE_STALE_S the node is evicted "
     "to unroutable."),
)


class _NodeScope:
    """One node's imported scope export plus scrape metadata."""

    __slots__ = ("node_id", "scraped_mono", "wall_offset_s", "rtt_s",
                 "export_bytes", "stage_rings", "slo_rings", "totals",
                 "top_waste_buckets", "synth_cache", "tenant_slos",
                 "tenant_waste")

    def __init__(self, node_id: str, scraped_mono: float,
                 wall_offset_s: float, rtt_s: float, export_bytes: int,
                 stage_rings: dict, slo_rings: dict, totals: dict,
                 top_waste_buckets: list,
                 synth_cache: Optional[dict] = None,
                 tenant_slos: Optional[dict] = None,
                 tenant_waste: Optional[list] = None):
        self.node_id = node_id
        self.scraped_mono = scraped_mono
        #: node wall clock minus router wall clock, measured against the
        #: fetch midpoint — what re-bases stitched traces
        self.wall_offset_s = wall_offset_s
        self.rtt_s = rtt_s
        self.export_bytes = export_bytes
        #: (stage, window label) -> [(age_s_at_scrape, QuantileSketch)]
        self.stage_rings = stage_rings
        #: (slo name, window label) -> (window_s, [(age_s, good, bad)])
        self.slo_rings = slo_rings
        self.totals = totals
        self.top_waste_buckets = top_waste_buckets
        #: the node's synthcache view (hit counters, bytes, hot_keys) —
        #: None on cache-off nodes; the fleet-cache replication pass
        #: reads hot_keys from here via node_cache_view
        self.synth_cache = synth_cache
        #: tenant -> (slo name, window label) -> (window_s, ring) —
        #: empty on tenancy-off nodes (ISSUE 17)
        self.tenant_slos = tenant_slos or {}
        #: the node's per-tenant padding-waste rows (ISSUE 17)
        self.tenant_waste = tenant_waste or []


class FleetScope:
    """Aggregate observability over a
    :class:`~sonata_tpu.serving.mesh.MeshRouter`'s membership.

    Attach with ``router.attach_fleet(fleet)``: the router's per-node
    prober threads then drive :meth:`on_probe_cycle`, so scraping
    inherits the prober's isolation (a wedged node stalls only its own
    thread).  All imports are validated at ingest — a malformed or
    version-mismatched export is counted, logged, and dropped whole.
    """

    def __init__(self, router, *, tracer=None,
                 scrape_interval_s: Optional[float] = None,
                 stale_s: Optional[float] = None,
                 recorder_cap: Optional[int] = None,
                 dump_dir: Optional[str] = None,
                 slos=None,
                 fetch: Optional[Callable[[str, float], tuple]] = None,
                 tick_interval_s: float = DEFAULT_TICK_INTERVAL_S,
                 clock=None):
        self.router = router
        self.tracer = tracer
        self._clock = clock if clock is not None else time.monotonic
        self.scrape_interval_s = max(0.05, (
            scrape_interval_s if scrape_interval_s is not None
            else _env_float(FLEET_SCRAPE_INTERVAL_ENV,
                            DEFAULT_SCRAPE_INTERVAL_S)))
        #: <= 0 disables staleness eviction (documented escape hatch)
        self.stale_s = (stale_s if stale_s is not None
                        else _env_float(FLEET_SCRAPE_STALE_ENV,
                                        DEFAULT_SCRAPE_STALE_S))
        self.recorder_cap = (recorder_cap if recorder_cap is not None
                             else _env_int(FLEET_RECORDER_CAP_ENV,
                                           DEFAULT_RECORDER_CAP))
        #: SONATA_FLEET_DUMP_DIR, falling back to the node recorder's
        #: SONATA_TIMELINE_DUMP_DIR so one knob configures both planes
        self.dump_dir = (dump_dir if dump_dir is not None
                         else os.environ.get(FLEET_DUMP_DIR_ENV)
                         or os.environ.get(DUMP_DIR_ENV) or None)
        self.slos = (parse_slos(slos)
                     if slos is None or isinstance(slos, str)
                     else list(slos))
        self._slo_by_name = {s.name: s for s in self.slos}
        self.tick_interval_s = max(0.05, tick_interval_s)
        self._fetch = fetch if fetch is not None else _http_fetch
        self._probe_timeout_s = getattr(router, "probe_timeout_s", 2.0)

        self._lock = threading.Lock()
        #: node.index -> _NodeScope (replaced whole per scrape)
        self._nodes: Dict[int, _NodeScope] = {}
        #: node.index -> monotonic stamp of the last scrape *attempt*
        self._attempt_at: Dict[int, float] = {}
        #: node.index -> first time this plane saw the node (staleness
        #: grace before the first successful scrape)
        self._first_seen: Dict[int, float] = {}
        #: nodes whose export answered 404: scope disabled there — not
        #: scrapeable, therefore never stale-evicted
        self._no_scope: set = set()
        self._gen = 0
        self._merged_lock = threading.Lock()
        self._merged_cache: Dict[tuple, tuple] = {}
        self.stats = {"scrapes": 0, "scrape_failures": 0,
                      "import_errors": 0}

        # fleet flight recorder
        self._timeline: "deque[dict]" = deque(
            maxlen=max(1, self.recorder_cap))
        self._timeline_lock = threading.Lock()
        self._last_dump_at: Dict[str, float] = {}
        self.dumps: List[str] = []
        #: edge-detection baselines.  Breaker trips are COUNTER edges,
        #: baselined at construction (zero trips) so a trip landing
        #: before the recorder's first 1 Hz tick still registers as an
        #: edge, not the baseline (caught by chaos phase M, where the
        #: injected trip beats the first tick).  Evictions are STATE
        #: edges and baseline at the first observed tick instead: a
        #: router booting before its backends would otherwise write a
        #: spurious node-evicted incident on every cold start.  Keyed
        #: by the stable node index, not node_id, so a scrape teaching
        #: the router a node's real id never reads as an eviction.
        self._last_routable_idx: Optional[frozenset] = None
        self._last_breaker_opens = 0
        self._last_burn_breach = False
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None

        # metric bookkeeping (lazy per-node series, exact teardown)
        self._registry = None
        self._node_families: dict = {}
        self._series_lock = threading.Lock()
        self._node_series: list = []        # (index, metric, labels)
        self._node_series_ids: Dict[int, str] = {}

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "FleetScope":
        """Start the 1 Hz fleet recorder thread (idempotent)."""
        if self._ticker is None or not self._ticker.is_alive():
            self._stop.clear()
            self._ticker = threading.Thread(target=self._tick_loop,
                                            name="sonata_fleet_tick",
                                            daemon=True)
            self._ticker.start()
        return self

    def close(self) -> None:
        self._stop.set()
        ticker, self._ticker = self._ticker, None
        if ticker is not None:
            ticker.join(timeout=2.0)
        self.unregister_node_series()

    def _tick_loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.tick()
            except Exception:
                # the recorder must never take the router down
                log.exception("fleet recorder tick failed")

    # -- scraping (rides the mesh prober threads) ------------------------------
    def on_probe_cycle(self, node) -> None:
        """Called by the router's prober after every health cycle for
        ``node``: scrape the scope export when the fleet cadence is
        due, and re-evaluate staleness every cycle (so eviction fires
        within one probe interval of the budget, not one scrape
        interval)."""
        if node.spec.metrics_base is None:
            return
        now = self._clock()
        with self._lock:
            self._first_seen.setdefault(node.index, now)
            last = self._attempt_at.get(node.index)
            due = last is None or now - last >= self.scrape_interval_s
            if due:
                self._attempt_at[node.index] = now
        if due:
            self.scrape_node(node)
        self._update_staleness(node)

    def scrape_node(self, node) -> bool:
        """One scope-export pull + ingest.  Returns whether an export
        was imported."""
        base = node.spec.metrics_base
        if base is None:
            return False
        t0_wall = time.time()
        try:
            code, body = self._fetch(base + "/debug/scope/export",
                                     self._probe_timeout_s)
        except Exception as e:
            with self._lock:
                self.stats["scrape_failures"] += 1
            log.debug("fleet: scope scrape of node %s failed: %s",
                      node.node_id, e)
            return False
        t1_wall = time.time()
        if code == 404:
            # scope disabled on that node (SONATA_SCOPE=0): it simply
            # does not report — never a fault, never stale-evicted.
            # Any export it reported BEFORE (e.g. pre-restart) is
            # dropped whole: a node that stopped exporting must not
            # stay "reporting" with an unboundedly-aging snapshot
            self._drop_node_scope(node)
            with self._lock:
                self._no_scope.add(node.index)
            return False
        if code != 200:
            with self._lock:
                self.stats["scrape_failures"] += 1
            return False
        try:
            payload = json.loads(body)
            self.ingest(node, payload,
                        wall_mid=(t0_wall + t1_wall) / 2.0,
                        rtt_s=t1_wall - t0_wall,
                        export_bytes=len(body))
        except SketchImportError as e:
            with self._lock:
                self.stats["import_errors"] += 1
            log.error("fleet: node %s scope export rejected: %s",
                      node.node_id, e)
            return False
        except ValueError as e:
            with self._lock:
                self.stats["import_errors"] += 1
            log.error("fleet: node %s scope export is not JSON: %s",
                      node.node_id, e)
            return False
        return True

    def ingest(self, node, payload, *, wall_mid: Optional[float] = None,
               rtt_s: float = 0.0, export_bytes: int = 0) -> None:
        """Validate and import one node's scope export (the whole
        payload is parsed up front — a malformed ring raises the typed
        :class:`SketchImportError` here, never lazily at query time)."""
        sketches._check_version(payload, "scope")
        stages = payload.get("stages")
        if not isinstance(stages, dict):
            raise SketchImportError("scope export has no 'stages' dict")
        stage_rings: dict = {}
        for stage, windows in stages.items():
            if not isinstance(windows, dict):
                raise SketchImportError(
                    f"scope export stage {stage!r} is not a dict")
            for label, ring_payload in windows.items():
                _w, _s, ring = sketches.ring_from_export(ring_payload)
                for _age, sk in ring:
                    # fleet merges are raw bucket adds: a node built
                    # with a different gamma must be rejected HERE,
                    # whole and typed, never folded (its bin keys mean
                    # different values)
                    if abs(sk.relative_accuracy
                           - sketches.DEFAULT_RELATIVE_ACCURACY) > 1e-12:
                        raise SketchImportError(
                            f"stage {stage!r}/{label}: node sketch "
                            f"relative_accuracy {sk.relative_accuracy} "
                            "differs from this router's "
                            f"{sketches.DEFAULT_RELATIVE_ACCURACY}")
                stage_rings[(stage, label)] = ring
        slo_rings: dict = {}
        for name, windows in (payload.get("slos") or {}).items():
            for label, ring_payload in dict(windows).items():
                # pre-parsed at ingest like the stage rings: burn
                # queries then only re-expire by age, no re-parsing on
                # the metrics scrape path
                window_s, _slot_s, ring = \
                    sketches.counter_ring_from_export(ring_payload)
                slo_rings[(name, label)] = (window_s, ring)
        # per-tenant SLO rings (ISSUE 17): same counter-ring format as
        # the global slos, one layer deeper — parsed whole at ingest so
        # a malformed tenant ring rejects the export typed, like the rest
        tenant_slos: dict = {}
        for tenant, tslos in (payload.get("tenant_slos") or {}).items():
            rings: dict = {}
            for name, windows in dict(tslos).items():
                for label, ring_payload in dict(windows).items():
                    window_s, _slot_s, ring = \
                        sketches.counter_ring_from_export(ring_payload)
                    rings[(name, label)] = (window_s, ring)
            tenant_slos[str(tenant)] = rings
        wall = payload.get("wall_time")
        offset = 0.0
        if isinstance(wall, (int, float)) and wall_mid is not None:
            offset = float(wall) - wall_mid
        ns = _NodeScope(
            node_id=node.node_id, scraped_mono=self._clock(),
            wall_offset_s=offset, rtt_s=rtt_s,
            export_bytes=export_bytes, stage_rings=stage_rings,
            slo_rings=slo_rings,
            totals=dict(payload.get("totals") or {}),
            top_waste_buckets=list(payload.get("top_waste_buckets")
                                   or ()),
            synth_cache=(dict(payload["synth_cache"])
                         if isinstance(payload.get("synth_cache"), dict)
                         else None),
            tenant_slos=tenant_slos,
            tenant_waste=list(payload.get("tenant_waste") or ()))
        with self._lock:
            self._nodes[node.index] = ns
            self._no_scope.discard(node.index)
            self._gen += 1
            self.stats["scrapes"] += 1
        self.router.record_scope_scrape(node)
        self._ensure_node_series(node)

    def _drop_node_scope(self, node) -> None:
        """Forget a node's imported export and its node_id-labeled
        series (a node that stopped exporting must not stay
        'reporting', inflate `sonata_fleet_nodes_reporting`, or page
        the scrape-age alert forever)."""
        with self._lock:
            had = self._nodes.pop(node.index, None) is not None
            if had:
                self._gen += 1
        if not had:
            return
        with self._series_lock:
            kept = []
            for idx, metric, labels in self._node_series:
                if idx == node.index:
                    metric.remove(**labels)
                else:
                    kept.append((idx, metric, labels))
            self._node_series = kept
            self._node_series_ids.pop(node.index, None)

    def _update_staleness(self, node) -> None:
        if self.stale_s <= 0 or node.spec.metrics_base is None:
            return
        now = self._clock()
        with self._lock:
            if node.index in self._no_scope:
                stale = False
            else:
                ns = self._nodes.get(node.index)
                ref = (ns.scraped_mono if ns is not None
                       else self._first_seen.get(node.index, now))
                stale = now - ref > self.stale_s
        # router lock taken outside the fleet lock (one-way ordering)
        self.router.set_scope_stale(node, stale)

    # -- fleet aggregation -----------------------------------------------------
    def _node_scopes(self) -> List[_NodeScope]:
        with self._lock:
            return list(self._nodes.values())

    def nodes_reporting(self) -> int:
        with self._lock:
            return len(self._nodes)

    def _merge_node_stage(self, ns: _NodeScope, stage: str,
                          window: str) -> Optional[QuantileSketch]:
        """One node's (stage, window) ring folded to a sketch, expiring
        slots by export age + scrape age (an export scraped 50 s ago
        contributes only what is still inside the window *now*)."""
        ring = ns.stage_rings.get((stage, window))
        if not ring:
            return None
        window_s = _WINDOW_SECONDS.get(window)
        if window_s is None:
            return None
        extra = self._clock() - ns.scraped_mono
        out = None
        for age_s, sketch in ring:
            if age_s + extra > window_s:
                continue
            if out is None:
                out = QuantileSketch(sketch.relative_accuracy)
            out.merge(sketch)
        return out

    def _merged(self, stage: str, window: str) -> QuantileSketch:
        """Fleet-merged sketch for (stage, window), memoized per
        (ingest generation, second) so one metrics scrape's 9 quantile
        callbacks per pair pay a single merge."""
        with self._lock:
            gen = self._gen
        stamp = (gen, int(self._clock()))
        key = (stage, window)
        with self._merged_lock:
            cached = self._merged_cache.get(key)
            if cached is not None and cached[0] == stamp:
                return cached[1]
        out = QuantileSketch()
        for ns in self._node_scopes():
            sk = self._merge_node_stage(ns, stage, window)
            if sk is not None and sk.count > 0:
                out.merge(sk)
        with self._merged_lock:
            self._merged_cache[key] = (stamp, out)
        return out

    def fleet_quantile(self, stage: str, q: float,
                       window: str) -> Optional[float]:
        """Fleet-wide quantile from the merged node exports, or None
        while no node has reported observations for the pair."""
        if stage not in STAGES or window not in _WINDOW_SECONDS:
            return None
        merged = self._merged(stage, window)
        if merged.count == 0:
            return None
        return merged.quantile(q)

    def _node_totals(self, ns: _NodeScope, slo: str,
                     window: str) -> tuple:
        entry = ns.slo_rings.get((slo, window))
        if entry is None:
            return 0, 0
        window_s, ring = entry
        extra = self._clock() - ns.scraped_mono
        good = bad = 0
        for age_s, g, b in ring:
            if age_s + extra > window_s:
                continue
            good += g
            bad += b
        return good, bad

    def fleet_burn_rate(self, slo: str,
                        window: str) -> Optional[float]:
        """Fleet bad fraction / budget over one window (node counters
        summed), or None while the fleet window is empty."""
        spec = self._slo_by_name.get(slo)
        if spec is None or window not in (FAST_WINDOW[0], SLOW_WINDOW[0]):
            return None
        good = bad = 0
        for ns in self._node_scopes():
            g, b = self._node_totals(ns, slo, window)
            good += g
            bad += b
        total = good + bad
        if total == 0:
            return None
        return (bad / total) / spec.budget

    def fleet_budget_remaining(self, slo: str) -> Optional[float]:
        burn = self.fleet_burn_rate(slo, SLOW_WINDOW[0])
        if burn is None:
            return None
        return 1.0 - burn

    def node_delta(self, node, stage: str) -> Optional[float]:
        """This node's 5m p99 minus the fleet-merged 5m p99 for
        ``stage`` (seconds; positive = slower than the fleet).  None
        until both sides have data."""
        with self._lock:
            ns = self._nodes.get(node.index)
        if ns is None:
            return None
        own = self._merge_node_stage(ns, stage, DELTA_WINDOW)
        if own is None or own.count == 0:
            return None
        fleet = self.fleet_quantile(stage, DELTA_QUANTILE, DELTA_WINDOW)
        own_q = own.quantile(DELTA_QUANTILE)
        if fleet is None or own_q is None:
            return None
        return own_q - fleet

    # -- the /debug/fleet scoreboard -------------------------------------------
    def fleet_snapshot(self) -> dict:
        """The JSON scoreboard: per-node health/occupancy/staleness/
        burn/deltas plus the fleet rollups."""
        view = self.router.snapshot()
        with self._lock:
            by_index = dict(self._nodes)
            no_scope = set(self._no_scope)
            stats = dict(self.stats)
        nodes_out = []
        for node in self.router.nodes:
            nv = node.snapshot()
            ns = by_index.get(node.index)
            entry = {**nv,
                     "reporting": ns is not None,
                     "scope_disabled": node.index in no_scope}
            if ns is not None:
                entry["export_age_s"] = round(
                    self._clock() - ns.scraped_mono, 3)
                entry["wall_offset_s"] = round(ns.wall_offset_s, 6)
                entry["totals"] = ns.totals
                entry["burn"] = {
                    spec.name: _round6(self._burn_of(ns, spec))
                    for spec in self.slos}
                entry["delta_p99_5m"] = {
                    stage: _round6(self.node_delta(node, stage))
                    for stage in STAGES}
                if ns.synth_cache is not None:
                    entry["synth_cache"] = ns.synth_cache
            nodes_out.append(entry)
        fleet_quant = {
            stage: {window: self._merged(stage, window).to_dict()
                    for window, _s in _WINDOW_SECONDS.items()}
            for stage in STAGES}
        fleet_slo = [{
            **spec.to_dict(),
            "burn_rate": {
                label: _round6(self.fleet_burn_rate(spec.name, label))
                for label in (FAST_WINDOW[0], SLOW_WINDOW[0])},
            "budget_remaining": _round6(
                self.fleet_budget_remaining(spec.name))}
            for spec in self.slos]
        # per-node voice-placement table (ISSUE 14): desired vs
        # converged holders, budgets, tombstones — served here so one
        # /debug/fleet load answers "where do this fleet's voices live"
        plane = getattr(self.router, "placement", None)
        placement = (plane.snapshot() if plane is not None
                     else None)
        # fleet cache tier (ISSUE 16): the router-side affinity/
        # replication view plus the node cache-counter rollup — one
        # /debug/fleet load answers "is the fleet cache working"
        fleetcache = getattr(self.router, "fleetcache", None)
        cache_rollup = self._cache_rollup(by_index.values())
        if fleetcache is not None:
            cache_rollup["router"] = fleetcache.snapshot()
        # multi-tenant rollup (ISSUE 17): fleet-merged per-tenant burn
        # plus the padding-waste chargeback — empty dict/list while the
        # fleet runs tenancy-off, so the document shape is stable
        tenant_burn = self.fleet_tenant_burn(by_index.values())
        tenant_waste = self._merged_tenant_waste(by_index.values())
        return {
            "name": view["name"],
            "routable": view["routable"],
            "router_stats": view["stats"],
            "scrape": {"interval_s": self.scrape_interval_s,
                       "stale_s": self.stale_s, **stats},
            "placement": placement,
            "nodes": nodes_out,
            "fleet": {
                "nodes_reporting": len(by_index),
                "stage_quantiles": fleet_quant,
                "slo": fleet_slo,
                "cache": cache_rollup,
                "top_waste_buckets": self._merged_waste_rows(
                    by_index.values()),
                "tenants": tenant_burn,
                "tenant_waste": tenant_waste,
            }}

    # -- fleet cache rollup (ISSUE 16) -----------------------------------------
    def node_cache_view(self, node) -> Optional[dict]:
        """The node's last-scraped synthcache view (None before one
        lands or on cache-off nodes) — the fleet-cache replication
        pass reads ``hot_keys`` from here."""
        with self._lock:
            ns = self._nodes.get(node.index)
        return None if ns is None else ns.synth_cache

    @staticmethod
    def _cache_rollup(node_scopes) -> dict:
        """Sum the reporting nodes' cache counters into the fleet view:
        fleet hit ratio (total hits over total resolved lookups),
        resident bytes/entries, and the reporting population."""
        hits = misses = bytes_used = entries = with_cache = 0
        for ns in node_scopes:
            sc = ns.synth_cache
            if not sc:
                continue
            with_cache += 1
            hits += int(sc.get("hits") or 0)
            misses += int(sc.get("misses") or 0)
            bytes_used += int(sc.get("bytes") or 0)
            entries += int(sc.get("entries") or 0)
        total = hits + misses
        return {"nodes_with_cache": with_cache, "hits": hits,
                "misses": misses, "bytes": bytes_used,
                "entries": entries,
                "hit_ratio": (round(hits / total, 6) if total else None)}

    def _burn_of(self, ns: _NodeScope, spec) -> Optional[float]:
        g, b = self._node_totals(ns, spec.name, FAST_WINDOW[0])
        total = g + b
        if total == 0:
            return None
        return (b / total) / spec.budget

    # -- per-tenant fleet burn (ISSUE 17) ---------------------------------------
    def _tenant_totals(self, ns: _NodeScope, tenant: str, slo: str,
                       window: str) -> tuple:
        entry = ns.tenant_slos.get(tenant, {}).get((slo, window))
        if entry is None:
            return 0, 0
        window_s, ring = entry
        extra = self._clock() - ns.scraped_mono
        good = bad = 0
        for age_s, g, b in ring:
            if age_s + extra > window_s:
                continue
            good += g
            bad += b
        return good, bad

    def fleet_tenant_burn(self, node_scopes=None) -> dict:
        """Fleet-merged per-tenant burn: tenant -> slo -> window ->
        bad fraction / budget (node counters summed), empty while no
        node exports tenant rings — the /debug/fleet 'tenants' block."""
        if node_scopes is None:
            node_scopes = self._node_scopes()
        tenants: set = set()
        for ns in node_scopes:
            tenants.update(ns.tenant_slos)
        out: dict = {}
        for tenant in sorted(tenants):
            per_slo: dict = {}
            for spec in self.slos:
                burns: dict = {}
                for label in (FAST_WINDOW[0], SLOW_WINDOW[0]):
                    good = bad = 0
                    for ns in node_scopes:
                        g, b = self._tenant_totals(
                            ns, tenant, spec.name, label)
                        good += g
                        bad += b
                    total = good + bad
                    if total:
                        burns[label] = _round6(
                            (bad / total) / spec.budget)
                if burns:
                    per_slo[spec.name] = burns
            if per_slo:
                out[tenant] = per_slo
        return out

    @staticmethod
    def _merged_tenant_waste(node_scopes) -> list:
        """Fleet per-tenant padding-waste chargeback: nodes' tenant
        rows summed by tenant, ranked by waste seconds."""
        acc: dict = {}
        for ns in node_scopes:
            for row in ns.tenant_waste:
                tenant = row.get("tenant")
                if not tenant:
                    continue
                slot = acc.setdefault(tenant, {
                    "tenant": tenant, "dispatches": 0,
                    "seconds": 0.0, "waste_seconds": 0.0})
                slot["dispatches"] += int(row.get("dispatches", 0))
                for k in ("seconds", "waste_seconds"):
                    slot[k] = round(slot[k] + float(row.get(k, 0.0)), 6)
        return sorted(acc.values(), key=lambda r: r["waste_seconds"],
                      reverse=True)

    @staticmethod
    def _merged_waste_rows(node_scopes, top: int = 10) -> list:
        """Fleet top waste buckets: nodes' top rows summed by bucket
        key.  Each node only exports its own top rows, so this is a
        lower bound per bucket — good enough to rank where the fleet's
        padding seconds go."""
        acc: dict = {}
        for ns in node_scopes:
            for row in ns.top_waste_buckets:
                key = (row.get("batch_bucket"), row.get("text_bucket"),
                       row.get("frame_bucket"))
                slot = acc.setdefault(key, {
                    "batch_bucket": key[0], "text_bucket": key[1],
                    "frame_bucket": key[2], "dispatches": 0, "rows": 0,
                    "padding_rows": 0, "seconds": 0.0,
                    "waste_seconds": 0.0, "cold_compiles": 0})
                for k in ("dispatches", "rows", "padding_rows",
                          "cold_compiles"):
                    slot[k] += int(row.get(k, 0))
                for k in ("seconds", "waste_seconds"):
                    slot[k] = round(slot[k] + float(row.get(k, 0.0)), 6)
        rows = sorted(acc.values(), key=lambda r: r["waste_seconds"],
                      reverse=True)
        return rows[:top]

    # -- stitched distributed traces -------------------------------------------
    def stitched_trace(self, request_id: str) -> tuple:
        """(http status, document) for ``/debug/traces/stitched?id=``:
        the router's span tree and the serving node's, spliced into one
        Chrome-trace JSON with the node's clock re-based through the
        scrape-measured wall offset."""
        if not request_id:
            return 400, {"error": "missing ?id=<request id>"}
        if self.tracer is None:
            return 404, {"error": "tracing not enabled on the router"}
        trace = self.tracer.find(request_id)
        if trace is None:
            return 404, {"error": f"no router trace for id "
                                  f"{request_id!r} (the ring holds the "
                                  f"{self.tracer.recent_cap} most "
                                  "recent traces)"}
        node_id = None
        for span in trace.spans_snapshot():
            if span.name == "mesh-dispatch" and span.attrs.get("node"):
                # the LAST mesh-dispatch is the attempt that served (or
                # terminally failed) the stream; earlier ones rerouted
                node_id = span.attrs["node"]
        events = [{"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
                   "args": {"name": f"sonata-mesh router "
                                    f"({self.router.name})"}}]
        events.extend(trace.chrome_events(tid=1, pid=1))
        stitched = {"request_id": request_id, "node": node_id,
                    "wall_offset_s": 0.0, "node_spans": 0}
        node_doc, err = self._fetch_node_trace(node_id, request_id)
        if node_doc is not None:
            offset = self._wall_offset_for(node_id)
            stitched["wall_offset_s"] = round(offset, 6)
            node_events = tracing.chrome_events_from_dict(
                node_doc, pid=2, tid=1, wall_offset_s=offset)
            stitched["node_spans"] = sum(
                1 for e in node_events if e.get("ph") == "X")
            events.append({"ph": "M", "pid": 2, "tid": 0,
                           "name": "process_name",
                           "args": {"name": f"node {node_id}"}})
            events.extend(node_events)
        elif err:
            stitched["node_error"] = err
        return 200, {"traceEvents": events, "displayTimeUnit": "ms",
                     "stitched": stitched}

    def _wall_offset_for(self, node_id: Optional[str]) -> float:
        with self._lock:
            for ns in self._nodes.values():
                if ns.node_id == node_id:
                    return ns.wall_offset_s
        return 0.0

    def _fetch_node_trace(self, node_id: Optional[str],
                          request_id: str) -> tuple:
        """(trace dict or None, error string or None)."""
        if node_id is None:
            return None, "router trace has no mesh-dispatch span"
        node = next((n for n in self.router.nodes
                     if n.node_id == node_id
                     or n.spec.addr == node_id), None)
        if node is None or node.spec.metrics_base is None:
            return None, (f"node {node_id!r} has no scrapeable "
                          "metrics plane")
        url = (node.spec.metrics_base + "/debug/traces?id="
               + urllib.parse.quote(request_id))
        try:
            code, body = self._fetch(url, self._probe_timeout_s)
            if code != 200:
                return None, f"node trace fetch answered {code}"
            traces = json.loads(body).get("traces") or []
        except Exception as e:
            return None, f"node trace fetch failed: {e}"
        if not traces:
            return None, (f"node {node_id} holds no trace for id "
                          f"{request_id!r}")
        return traces[0], None

    # -- fleet flight recorder -------------------------------------------------
    def tick(self) -> dict:
        """One 1 Hz fleet snapshot (the recorder thread calls this;
        tests call it directly).  Auto-dump triggers — node eviction,
        breaker trip, fleet fast-burn breach — are edge-detected here
        so they cost nothing anywhere else."""
        view = self.router.snapshot()
        snap: dict = {"ts": round(time.time(), 3),
                      "routable": view["routable"],
                      "nodes_reporting": self.nodes_reporting(),
                      "rerouted": view["stats"].get("rerouted", 0),
                      "failed": view["stats"].get("failed", 0)}
        nodes: dict = {}
        routable_idx = set()
        for nv in view["nodes"]:
            nodes[nv["node_id"]] = {
                "state": nv["state"], "draining": nv["draining"],
                "ready": nv["ready"],
                "outstanding": nv["outstanding"],
                "scope_stale": nv["scope_stale"],
                "scrape_age_s": nv["scope_scrape_age_s"]}
            if (nv["state"] != "open" and nv["ready"]
                    and not nv["draining"] and not nv["scope_stale"]):
                routable_idx.add(nv["index"])
        snap["nodes"] = nodes
        breach = False
        for spec in self.slos:
            burn = self.fleet_burn_rate(spec.name, FAST_WINDOW[0])
            if burn is None:
                continue
            snap[f"burn:{spec.name}"] = round(burn, 3)
            if burn > 1.0:
                breach = True
        snap["fleet_burn_breach"] = 1 if breach else 0
        with self._timeline_lock:
            self._timeline.append(snap)
        # edge-detected incident dumps (per-reason rate-limited)
        evicted = (self._last_routable_idx is not None
                   and bool(self._last_routable_idx
                            - frozenset(routable_idx)))
        self._last_routable_idx = frozenset(routable_idx)
        opens = view["stats"].get("breaker_opens", 0)
        tripped = opens > self._last_breaker_opens
        self._last_breaker_opens = opens
        burn_crossed = breach and not self._last_burn_breach
        self._last_burn_breach = breach
        if evicted:
            self.dump("node-evicted")
        if tripped:
            self.dump("breaker-trip")
        if burn_crossed:
            self.dump("fleet-burn")
        return snap

    def timeline_snapshot(self) -> list:
        with self._timeline_lock:
            return list(self._timeline)

    def dump(self, reason: str) -> Optional[str]:
        """Write the fleet timeline ring to ``dump_dir`` (no-op when
        unset), at most once per ``DUMP_MIN_INTERVAL_S`` per reason —
        the PR-7 rate-limit contract, so a flapping breaker cannot
        starve a burn incident of its dump."""
        if not self.dump_dir:
            return None
        now = self._clock()
        with self._timeline_lock:
            last = self._last_dump_at.get(reason)
            if last is not None and now - last < DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump_at[reason] = now
            snapshots = list(self._timeline)
        path = os.path.join(
            self.dump_dir, f"fleet-{int(time.time())}-{reason}.json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump({"reason": reason, "wall_time": time.time(),
                           "interval_s": self.tick_interval_s,
                           "snapshots": snapshots}, f)
        except OSError:
            log.exception("fleet recorder dump to %s failed", path)
            return None
        self.dumps.append(path)
        log.warning("fleet recorder dumped %d snapshot(s) to %s (%s)",
                    len(snapshots), path, reason)
        return path

    # -- metrics export --------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Attach the fleet gauge families (loop-registered literal
        tables, the scope idiom).  The fixed-label families bind now;
        node_id-labeled series appear lazily at first ingest (the
        node's stable id is only known once its export is scraped) and
        are torn down exactly by :meth:`unregister_node_series`."""
        self._registry = registry
        families = {}
        for name, help in FLEET_GAUGE_FAMILIES:
            families[name] = registry.gauge(name, help)
        quant = families["sonata_fleet_stage_quantile"]
        for stage in STAGES:
            for wlabel, _s, _n in WINDOWS:
                for qlabel, q in QUANTILES:
                    quant.labels(
                        stage=stage, q=qlabel, window=wlabel
                    ).set_function(
                        lambda s=stage, qq=q, w=wlabel:
                        self.fleet_quantile(s, qq, w))
        burn = families["sonata_fleet_slo_burn_rate"]
        for spec in self.slos:
            for wlabel in (FAST_WINDOW[0], SLOW_WINDOW[0]):
                burn.labels(slo=spec.name, window=wlabel).set_function(
                    lambda n=spec.name, w=wlabel:
                    self.fleet_burn_rate(n, w))
        families["sonata_fleet_nodes_reporting"].set_function(
            lambda: float(self.nodes_reporting()))
        for name, help in FLEET_NODE_GAUGE_FAMILIES:
            self._node_families[name] = registry.gauge(name, help)

    def _ensure_node_series(self, node) -> None:
        """Create (or re-key, if a scrape taught us a new node_id) the
        node_id-labeled series for ``node``; every created series is
        recorded so teardown removes exactly what was registered."""
        if self._registry is None:
            return
        with self._series_lock:
            current = self._node_series_ids.get(node.index)
            if current == node.node_id:
                return
            if current is not None:
                kept = []
                for idx, metric, labels in self._node_series:
                    if idx == node.index:
                        metric.remove(**labels)
                    else:
                        kept.append((idx, metric, labels))
                self._node_series = kept
            nid = node.node_id
            age = self._node_families.get(
                "sonata_mesh_node_scrape_age_seconds")
            if age is not None:
                labels = {"node_id": nid}
                age.labels(**labels).set_function(
                    lambda n=node: self.router.scope_scrape_age_s(n))
                self._node_series.append((node.index, age, labels))
            delta = self._node_families.get("sonata_fleet_node_delta")
            if delta is not None:
                for stage in STAGES:
                    labels = {"node_id": nid, "stage": stage}
                    delta.labels(**labels).set_function(
                        lambda n=node, s=stage: self.node_delta(n, s))
                    self._node_series.append((node.index, delta, labels))
            self._node_series_ids[node.index] = nid

    def unregister_node_series(self) -> None:
        """Drop every node_id-labeled series created at ingest (the
        teardown twin of the lazy registration in
        :meth:`_ensure_node_series`)."""
        with self._series_lock:
            for _idx, metric, labels in self._node_series:
                metric.remove(**labels)
            self._node_series = []
            self._node_series_ids = {}


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)
