"""sonata-synthcache: content-addressed request-level synthesis cache.

At consumer scale TTS traffic is dominated by repeated strings
(notification templates, IVR prompts, UI text), yet every request runs
the full phonemize→VITS→epilogue pipeline even when the engine
synthesized the identical utterance milliseconds ago.  This module turns
the hottest requests into a memcpy:

- **Content-addressed.**  Entries are keyed by :func:`request_key` — a
  blake2b digest of the canonical request identity: the
  whitespace/casing-normalized text (:func:`canonical_text`), voice id,
  speaker id, length/noise/noise-w scales, output sample-rate/format,
  and the stream-shape fields (RPC kind, synthesis mode, realtime chunk
  schedule).  Never Python ``hash()`` — the key is pinned stable across
  processes so a fleet of replicas agrees on identity.
- **Chunk-exact replay.**  An entry stores the finished stream as its
  ordered i16 chunk list (the exact wire payloads the miss produced), so
  a hit replays the same chunk sequence byte for byte: clients, the
  crossfade seams, and the trace shape are indistinguishable from the
  synthesis that filled the entry.
- **Write-through LRU bounded by bytes.**  ``SONATA_SYNTH_CACHE_MB``
  (0 = off, the default — the pre-cache request path is byte-for-byte
  unchanged) bounds the committed chunk bytes; inserting past the budget
  evicts least-recently-used entries first.  An entry is inserted only
  on FULLY-successful synthesis — a failed, cancelled, or
  deadline-expired stream never caches a truncated result.
- **Single-flight dedup.**  N concurrent identical requests admit ONE
  synthesizer (the leader, who fills the entry); the other N−1 stream
  chunks from the filling entry as they land.  Follower waits are
  bounded per chunk by ``SONATA_SYNTH_CACHE_WAIT_S``; on leader failure
  (or a stalled leader) a follower that has not yet emitted audio falls
  back to independent synthesis — a leader error must not fan out.  A
  follower the leader fails MID-stream raises
  :class:`LeaderFailed` typed instead (the mesh rule: re-sending audio
  from an independent — differently-noised — synthesis is worse than
  failing).
- **Failpoint.**  Every :meth:`SynthCache.lookup` fires the
  ``cache.lookup`` site; an injected (or real) lookup error degrades to
  a normal miss that bypasses the cache entirely — a broken cache can
  never fail a request.
- **Observability.**  ``sonata_synth_cache_{hits,misses,inserts,
  evictions}_total`` and ``sonata_synth_cache_bytes`` on the metrics
  plane (counter semantics via scrape-time callbacks — the hot path
  bumps plain ints under the cache lock), plus hit-ratio rows on the
  scope plane (``/debug/quantiles`` ``synth_cache`` section and the
  flight recorder's ``cache_hit_ratio`` probe).

The cache is owned by the
:class:`~sonata_tpu.serving.ServingRuntime` and wired into the request
path in ``frontends/grpc_server.py`` AHEAD of pool/iteration-loop
admission: hits bypass queue wait entirely and stamp a ``cache-hit``
trace span.  Nothing here imports gRPC or jax.
"""

from __future__ import annotations

import hashlib
import logging
import os
import struct
import threading
import time
import unicodedata
from collections import OrderedDict
from typing import Iterator, Optional, Tuple

from ..core import OperationError
from . import faults

log = logging.getLogger("sonata.serving")

CACHE_MB_ENV = "SONATA_SYNTH_CACHE_MB"
CACHE_WAIT_S_ENV = "SONATA_SYNTH_CACHE_WAIT_S"
CASEFOLD_ENV = "SONATA_SYNTH_CACHE_CASEFOLD"

DEFAULT_WAIT_S = 10.0
#: per-chunk bookkeeping estimate added to the payload length so a
#: thousand tiny chunks cannot hide from the byte budget
CHUNK_OVERHEAD_BYTES = 64

#: key-schema version: bump whenever the canonical tuple changes shape,
#: so stale cross-process assumptions about identity fail to collide
#: instead of colliding wrong.  v2: the voice scales are canonicalized
#: through float32 (the wire precision of SynthesisOptions), so a key
#: derived at the mesh router from wire-learned options is byte-identical
#: to the node's key derived from its float64 config.
KEY_VERSION = "v2"

#: how many LRU-head keys :meth:`SynthCache.cache_view` advertises for
#: fleet hot-set replication (sonata-fleetcache) — a view shape, not a
#: replication policy (``SONATA_FLEETCACHE_REPLICATE_K`` bounds how many
#: the router actually replicates)
HOT_KEYS_MAX = 16

_FILLING, _COMPLETE, _FAILED = "filling", "complete", "failed"

#: one chunk as stored and replayed: (wire payload bytes, aux float) —
#: aux carries the per-sentence RTF for SynthesizeUtterance results and
#: is None for realtime wave chunks
Chunk = Tuple[bytes, Optional[float]]


class LeaderFailed(OperationError):
    """The single-flight leader failed (or stalled past the bounded
    wait) while this follower was streaming from its filling entry."""


def resolve_casefold() -> bool:
    """``SONATA_SYNTH_CACHE_CASEFOLD`` (the one default-defining read):
    1 / unset / unparseable = casefold (the PR-15 behavior), 0 = keep
    case as part of textual identity.  Read at canonicalization time so
    the trade-off can be flipped per process without a restart dance in
    tests."""
    raw = os.environ.get(CASEFOLD_ENV, "").strip()
    if not raw:
        return True
    try:
        return int(raw) != 0
    except ValueError:
        log.warning("ignoring non-numeric %s=%r (casefold stays on)",
                    CASEFOLD_ENV, raw)
        return True


def canonical_text(text: str) -> str:
    """The cache's one definition of textual identity: Unicode NFC,
    casefolded, whitespace runs collapsed to single spaces, stripped.
    ``" Hello\\n\\tWORLD "`` and ``"hello world"`` address one entry.

    Casefolding is a documented trade-off (DEPLOY.md): eSpeak can
    pronounce casing ("US" vs "us"), so case-divergent texts share the
    entry of whoever synthesized first — template traffic is
    case-stable, which is what this cache exists for.  Deployments whose
    traffic IS case-sensitive opt out with
    ``SONATA_SYNTH_CACHE_CASEFOLD=0``: case-divergent texts then
    address separate entries (no key-schema change needed — the texts
    simply stop collapsing)."""
    normalized = unicodedata.normalize("NFC", text)
    if resolve_casefold():
        normalized = normalized.casefold()
    return " ".join(normalized.split())


def _num(v) -> str:
    """Canonical numeric rendering (``repr`` floats round-trip exactly;
    ints stay ints) so 1.0 and 1 cannot split an identity."""
    if v is None:
        return "-"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _f32(v: Optional[float]) -> Optional[float]:
    """Round-trip a scale through IEEE float32 — the precision the
    SynthesisOptions wire fields carry.  The node configures scales as
    float64 (``0.667``) but the mesh router learns them from protobuf
    floats (``0.6669999957…``); canonicalizing BOTH sides through
    float32 makes the router-derived affinity key byte-identical to the
    node-derived cache key (pinned by tests/test_fleetcache.py)."""
    if v is None:
        return None
    return struct.unpack("<f", struct.pack("<f", float(v)))[0]


def request_key(*, rpc: str, text: str, voice_id: str,
                speaker: Optional[int],
                length_scale: float, noise_scale: float, noise_w: float,
                sample_rate: int, sample_width: int, channels: int,
                mode: int = 0, chunk_size: int = 0, chunk_padding: int = 0,
                speech_args: Optional[tuple] = None) -> str:
    """Content address of one synthesis request.

    A blake2b digest of the canonical tuple — NOT Python ``hash()``
    (whose strings are salted per process): the derivation is pinned
    stable across processes by test_synthcache's golden digest.
    ``speech_args`` is the raw (rate, volume, pitch,
    appended_silence_ms) tuple or None; any prosody post-processing
    changes the audio, so it is part of identity.
    """
    sa = "-" if speech_args is None else ",".join(
        _num(x) for x in speech_args)
    parts = (KEY_VERSION, rpc, canonical_text(text), voice_id,
             _num(speaker), _num(_f32(length_scale)),
             _num(_f32(noise_scale)), _num(_f32(noise_w)),
             _num(sample_rate), _num(sample_width),
             _num(channels), _num(mode), _num(chunk_size),
             _num(chunk_padding), sa)
    blob = "\x1f".join(parts).encode("utf-8")
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def utterance_key(kind: str, request, *, voice_id: str,
                  speaker: Optional[int], length_scale: float,
                  noise_scale: float, noise_w: float, sample_rate: int,
                  sample_width: int, channels: int) -> str:
    """:func:`request_key` for one decoded ``pb.Utterance`` plus the
    per-voice identity fields the caller holds.

    This is THE shared derivation for the request-shape half of the key
    (synthesis mode, realtime chunk-schedule defaults, speech-args
    flattening): the node frontend (``grpc_server._cache_key_for``) and
    the mesh router (``serving/fleetcache.py``) both call it, so the
    two sides cannot drift on how an Utterance maps into the canonical
    tuple — only on the per-voice fields, which the key-parity tests
    pin separately."""
    sa = request.speech_args
    realtime = kind == "realtime"
    return request_key(
        rpc=kind, text=request.text, voice_id=voice_id, speaker=speaker,
        length_scale=length_scale, noise_scale=noise_scale,
        noise_w=noise_w, sample_rate=sample_rate,
        sample_width=sample_width, channels=channels,
        mode=request.synthesis_mode or 0,
        chunk_size=(request.realtime_chunk_size or 55) if realtime else 0,
        chunk_padding=(request.realtime_chunk_padding or 3) if realtime
        else 0,
        speech_args=None if sa is None else (
            sa.rate, sa.volume, sa.pitch, sa.appended_silence_ms))


def resolve_cache_mb() -> float:
    """``SONATA_SYNTH_CACHE_MB`` (the one default-defining read): 0 /
    unset / unparseable = off.  Fractional megabytes are honored — the
    smoke lanes size the budget below one entry-set on purpose."""
    raw = os.environ.get(CACHE_MB_ENV, "").strip()
    if not raw:
        return 0.0
    try:
        mb = float(raw)
    except ValueError:
        log.warning("ignoring non-numeric %s=%r (cache stays off)",
                    CACHE_MB_ENV, raw)
        return 0.0
    return max(mb, 0.0)


def resolve_wait_s() -> float:
    """``SONATA_SYNTH_CACHE_WAIT_S``: the bounded per-chunk follower
    wait before a stalled leader is treated as failed."""
    try:
        return max(0.1, float(os.environ.get(CACHE_WAIT_S_ENV,
                                             DEFAULT_WAIT_S)))
    except ValueError:
        return DEFAULT_WAIT_S


def from_env() -> Optional["SynthCache"]:
    """The runtime's construction gate: a :class:`SynthCache` when
    ``SONATA_SYNTH_CACHE_MB`` > 0, else None (the default — every cache
    hook then costs one ``is None`` branch)."""
    mb = resolve_cache_mb()
    if mb <= 0:
        return None
    return SynthCache(max_bytes=int(mb * 1024 * 1024),
                      wait_s=resolve_wait_s())


class _Entry:
    """One cached (or filling) stream.  ``chunks`` is append-only while
    filling and frozen after the terminal transition; readers and the
    filling writer synchronize on ``cond``.  ``tag`` groups entries for
    invalidation (the frontends tag by voice id, so :meth:`SynthCache.
    drop_tag` can purge a voice's streams on unload/reload)."""

    __slots__ = ("key", "chunks", "bytes", "state", "cond", "tag",
                 "invalidated", "owner")

    def __init__(self, key: str, tag: Optional[str] = None,
                 owner: Optional[str] = None):
        self.key = key
        self.chunks: list = []          # [(payload, aux), ...]
        self.bytes = 0
        self.state = _FILLING
        self.cond = threading.Condition()
        self.tag = tag
        #: the tenant whose miss filled this entry (sonata-tenancy).
        #: Ownership bounds the tenant's INSERT budget only — the key
        #: is tenant-free, so other tenants' identical requests hit
        #: this entry without charging anyone's share twice.
        self.owner = owner
        #: set (under the registry lock) by drop_tag while this entry
        #: is still filling: the fill keeps streaming to its clients,
        #: but its commit must not insert — the tag's voice was
        #: unloaded mid-fill, and a reload at the same id would hit
        #: stale audio
        self.invalidated = False

    def view(self) -> dict:
        return {"key": self.key, "chunks": len(self.chunks),
                "bytes": self.bytes, "state": self.state,
                "tag": self.tag, "owner": self.owner}


class FillHandle:
    """The single-flight leader's handle: tee every emitted chunk in,
    then exactly one of :meth:`commit_fill` (fully-successful stream →
    write-through insert) or :meth:`abort_fill` (any other exit — the
    truncated result is discarded and waiting followers are released
    into their fallback)."""

    __slots__ = ("_cache", "_entry", "_done")

    def __init__(self, cache: "SynthCache", entry: _Entry):
        self._cache = cache
        self._entry = entry
        self._done = False

    def add_chunk(self, payload: bytes, aux: Optional[float] = None
                  ) -> None:
        entry = self._entry
        with entry.cond:
            entry.chunks.append((payload, aux))
            entry.bytes += len(payload) + CHUNK_OVERHEAD_BYTES
            entry.cond.notify_all()

    def commit_fill(self) -> None:
        if self._done:
            return
        self._done = True
        self._cache._commit(self._entry)

    def abort_fill(self) -> None:
        if self._done:
            return
        self._done = True
        self._cache._abort(self._entry)


class FollowerStream:
    """A deduplicated request streaming chunks from a filling entry as
    the leader lands them.  Iteration yields :data:`Chunk` tuples;
    exhaustion means the leader committed.  :class:`LeaderFailed` is
    raised when the leader aborted or stalled past the bounded per-chunk
    wait — the caller falls back to independent synthesis if (and only
    if) it has not emitted audio yet."""

    __slots__ = ("_cache", "_entry", "_i", "_wait_s", "_resolved")

    def __init__(self, cache: "SynthCache", entry: _Entry, wait_s: float):
        self._cache = cache
        self._entry = entry
        self._i = 0
        self._wait_s = wait_s
        self._resolved = False

    def __iter__(self) -> Iterator[Chunk]:
        return self

    def __next__(self) -> Chunk:
        entry = self._entry
        with entry.cond:
            deadline = time.monotonic() + self._wait_s
            while True:
                if self._i < len(entry.chunks):
                    chunk = entry.chunks[self._i]
                    self._i += 1
                    return chunk
                if entry.state == _COMPLETE:
                    self._resolve(hit=True)
                    raise StopIteration
                if entry.state == _FAILED:
                    self._resolve(hit=False)
                    raise LeaderFailed(
                        "synthesis cache leader failed while filling "
                        f"entry {entry.key[:12]}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._resolve(hit=False)
                    raise LeaderFailed(
                        "synthesis cache leader stalled past the "
                        f"{self._wait_s:g}s follower wait "
                        f"({CACHE_WAIT_S_ENV})")
                entry.cond.wait(timeout=remaining)

    def abandon(self) -> None:
        """Resolve a follower the caller walked away from mid-follow
        (client disconnect) as a miss, so every follower lookup reaches
        exactly one terminal count.  No-op once resolved."""
        self._resolve(hit=False)

    def _resolve(self, hit: bool) -> None:
        """Count this follower exactly once at its terminal state: a
        follower served whole from the entry is a hit; one that must
        fall back (or fail, or is abandoned) is a miss."""
        if self._resolved:
            return
        self._resolved = True
        self._cache._note_follower(hit)


class SynthCache:
    """Byte-bounded write-through LRU of finished synthesis streams
    with single-flight fill dedup.  Thread-safe; the registry lock is
    held only for dict bookkeeping (never across a wait or a chunk
    copy)."""

    def __init__(self, max_bytes: int, wait_s: float = DEFAULT_WAIT_S):
        if max_bytes <= 0:
            raise ValueError("SynthCache needs a positive byte budget "
                             "(use from_env() for the 0=off gate)")
        self.max_bytes = int(max_bytes)
        self.wait_s = float(wait_s)
        self._lock = threading.Lock()
        #: committed entries, LRU order (oldest first)
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        #: single-flight: key -> the entry a leader is filling
        self._filling: dict = {}
        self._bytes = 0
        self._closed = False
        self._stats = {"hits": 0, "misses": 0, "inserts": 0,
                       "evictions": 0, "follower_joins": 0,
                       "lookup_errors": 0, "oversize_skips": 0,
                       "invalidations": 0, "share_evictions": 0}
        #: sonata-tenancy insert budgets: owner tenant -> committed
        #: bytes, and the resolver mapping an owner to its fraction of
        #: max_bytes (None = unshared).  Wired by the runtime when both
        #: planes are enabled; absent, nothing below changes behavior.
        self._owner_bytes: dict = {}
        self._share_of = None

    def set_share_resolver(self, share_of) -> None:
        """Attach the tenancy plane's ``cache_share`` resolver: owner →
        fraction of ``max_bytes`` that owner's committed entries may
        hold (None = unshared).  Enforced at commit time — one tenant's
        template churn then evicts its OWN least-recent entries first
        and can never flush another tenant's hot set."""
        self._share_of = share_of

    # -- the request-path surface --------------------------------------------
    def lookup(self, key: str, tag: Optional[str] = None,
               owner: Optional[str] = None):
        """Probe the cache for ``key``.  Returns one of:

        - ``("hit", chunks)`` — a committed entry; ``chunks`` is its
          frozen ordered chunk list, replayable without further locking
          (eviction only unlinks the entry, the list stays alive with
          its readers);
        - ``("follow", FollowerStream)`` — another identical request is
          filling the entry right now (counted at the follower's
          terminal state, not here);
        - ``("fill", FillHandle)`` — a miss; the caller is the
          single-flight leader and must commit or abort the handle;
        - ``("bypass", None)`` — the lookup itself failed (the
          ``cache.lookup`` failpoint, or any unexpected internal
          error): degrade to a normal miss that leaves the cache alone
          — a broken cache can never fail a request.

        ``tag`` labels a new fill's entry for group invalidation
        (:meth:`drop_tag`); the frontends tag by voice id.  ``owner``
        names the tenant whose miss fills the entry (sonata-tenancy:
        commit-time insert budgets) — NEVER part of the key, so
        identical requests across tenants still dedup to one entry.
        """
        try:
            faults.fire("cache.lookup")
            with self._lock:
                if self._closed:
                    return ("bypass", None)
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self._stats["hits"] += 1
                    return ("hit", entry.chunks)
                filling = self._filling.get(key)
                if filling is not None:
                    self._stats["follower_joins"] += 1
                    return ("follow",
                            FollowerStream(self, filling, self.wait_s))
                entry = _Entry(key, tag=tag, owner=owner)
                self._filling[key] = entry
                self._stats["misses"] += 1
                return ("fill", FillHandle(self, entry))
        except Exception:
            # injected or real: one probe degrades, the request lives
            with self._lock:
                self._stats["lookup_errors"] += 1
                self._stats["misses"] += 1
            log.debug("synth-cache lookup degraded to a miss",
                      exc_info=True)
            return ("bypass", None)

    # -- fill resolution (FillHandle calls these) ----------------------------
    def _owner_budget_locked(self, owner: Optional[str]) -> Optional[int]:
        """The owner tenant's committed-byte ceiling, or None (unshared
        — the pre-tenancy behavior, and the behavior for any tenant
        with no configured ``cache_share``)."""
        if owner is None or self._share_of is None:
            return None
        try:
            share = self._share_of(owner)
        except Exception:
            return None
        if share is None or share <= 0:
            return None
        return int(min(1.0, share) * self.max_bytes)

    def _unlink_locked(self, key: str) -> "_Entry":
        old = self._entries.pop(key)
        self._bytes -= old.bytes
        if old.owner is not None:
            left = self._owner_bytes.get(old.owner, 0) - old.bytes
            if left > 0:
                self._owner_bytes[old.owner] = left
            else:
                self._owner_bytes.pop(old.owner, None)
        return old

    def _commit(self, entry: _Entry) -> None:
        evicted = []
        with self._lock:
            self._filling.pop(entry.key, None)
            budget = self._owner_budget_locked(entry.owner)
            if entry.invalidated:
                # the tag was dropped mid-fill (voice unload/reload):
                # the stream served its clients, the entry must not land
                self._stats["invalidations"] += 1
            elif (not self._closed and entry.bytes <= self.max_bytes
                    and (budget is None or entry.bytes <= budget)):
                # per-tenant insert budget (sonata-tenancy): the owner's
                # committed bytes stay under its share by evicting the
                # owner's OWN least-recent entries first — a churning
                # tenant can never flush another tenant's hot set
                if budget is not None:
                    while (self._owner_bytes.get(entry.owner, 0)
                           + entry.bytes > budget):
                        doomed = next(
                            (k for k, e in self._entries.items()
                             if e.owner == entry.owner), None)
                        if doomed is None:
                            break
                        evicted.append(self._unlink_locked(doomed).key[:12])
                        self._stats["evictions"] += 1
                        self._stats["share_evictions"] += 1
                self._entries[entry.key] = entry
                self._entries.move_to_end(entry.key)
                self._bytes += entry.bytes
                if entry.owner is not None:
                    self._owner_bytes[entry.owner] = (
                        self._owner_bytes.get(entry.owner, 0) + entry.bytes)
                self._stats["inserts"] += 1
                while self._bytes > self.max_bytes:
                    k = next(iter(self._entries))
                    evicted.append(self._unlink_locked(k).key[:12])
                    self._stats["evictions"] += 1
            elif not self._closed:
                # one stream bigger than the whole budget (or the
                # owner's whole share): caching it would evict
                # everything it is allowed to hold and immediately
                # evict itself
                self._stats["oversize_skips"] += 1
        with entry.cond:
            entry.state = _COMPLETE
            entry.cond.notify_all()
        if evicted:
            log.debug("synth-cache evicted %d entr%s (budget %d bytes)",
                      len(evicted), "y" if len(evicted) == 1 else "ies",
                      self.max_bytes)

    def _abort(self, entry: _Entry) -> None:
        with self._lock:
            self._filling.pop(entry.key, None)
        with entry.cond:
            entry.state = _FAILED
            entry.cond.notify_all()

    def _note_follower(self, hit: bool) -> None:
        with self._lock:
            self._stats["hits" if hit else "misses"] += 1

    # -- invalidation --------------------------------------------------------
    def drop_tag(self, tag: Optional[str]) -> int:
        """Drop every committed entry filed under ``tag`` (the frontends
        tag by voice id: UnloadVoice must purge the voice's streams, or
        a model reloaded at the same config path — same voice id —
        would replay the OLD model's audio as hits).  A fill still in
        flight keeps streaming to its clients, but its entry is marked
        invalidated so its commit refuses to insert.  Returns the number
        of committed entries dropped."""
        if tag is None:
            return 0
        with self._lock:
            doomed = [k for k, e in self._entries.items() if e.tag == tag]
            for k in doomed:
                self._unlink_locked(k)
            self._stats["invalidations"] += len(doomed)
            for e in self._filling.values():
                if e.tag == tag:
                    e.invalidated = True
            return len(doomed)

    # -- introspection / metrics ---------------------------------------------
    def stat(self, name: str) -> float:
        with self._lock:
            return float(self._stats[name])

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def hit_ratio(self) -> Optional[float]:
        """hits / (hits + misses), or None before any lookup resolved."""
        with self._lock:
            total = self._stats["hits"] + self._stats["misses"]
            if total == 0:
                return None
            return self._stats["hits"] / total

    def cache_view(self) -> dict:
        """One snapshot for the scope plane's ``synth_cache`` rows.

        ``hot_keys`` is the LRU head — up to :data:`HOT_KEYS_MAX` keys,
        most-recently-used first.  It rides the scope export so the mesh
        router's fleetcache can see each node's hot set and replicate it
        to the rendezvous peer (sonata-fleetcache)."""
        with self._lock:
            ratio = None
            total = self._stats["hits"] + self._stats["misses"]
            if total:
                ratio = round(self._stats["hits"] / total, 6)
            hot = list(self._entries)[-HOT_KEYS_MAX:]
            hot.reverse()
            view = {**self._stats, "hit_ratio": ratio,
                    "bytes": self._bytes, "entries": len(self._entries),
                    "max_bytes": self.max_bytes,
                    "filling": len(self._filling),
                    "hot_keys": hot}
            if self._owner_bytes:
                # per-tenant resident bytes (chargeback rows; absent
                # pre-tenancy — importers use .get, no shape break)
                view["owner_bytes"] = dict(sorted(
                    self._owner_bytes.items()))
            return view

    def bind_metrics(self, registry) -> None:
        """Attach the cache's series as scrape-time callbacks.  The
        series exist only on cache-enabled processes (the knob/metric
        pair appears and disappears together); they are process-lifetime
        like the failpoint counters, so there is no per-voice teardown
        to record — :meth:`close` ends the process's cache story whole."""
        registry.counter(
            "sonata_synth_cache_hits_total",
            "Synthesis-cache lookups served from a committed entry "
            "(including single-flight followers served whole from a "
            "filling entry)."
        ).set_function(lambda: self.stat("hits"))
        registry.counter(
            "sonata_synth_cache_misses_total",
            "Synthesis-cache lookups that ran a real synthesis "
            "(including degraded lookups and follower fallbacks)."
        ).set_function(lambda: self.stat("misses"))
        registry.counter(
            "sonata_synth_cache_inserts_total",
            "Fully-successful synthesis streams committed into the "
            "cache (write-through; failed/cancelled streams never "
            "insert)."
        ).set_function(lambda: self.stat("inserts"))
        registry.counter(
            "sonata_synth_cache_evictions_total",
            "Entries evicted LRU-first to hold the "
            "SONATA_SYNTH_CACHE_MB byte budget."
        ).set_function(lambda: self.stat("evictions"))
        registry.gauge(
            "sonata_synth_cache_bytes",
            "Committed synthesis-cache bytes (chunk payloads + "
            "per-chunk overhead) currently resident."
        ).set_function(lambda: float(self.bytes_used))

    def close(self) -> None:
        """Drop every committed entry and refuse further inserts.
        In-flight fills resolve against their own entry objects; their
        commit lands on a closed registry and is discarded."""
        with self._lock:
            self._closed = True
            self._entries.clear()
            self._owner_bytes.clear()
            self._bytes = 0
