"""sonata-tpu: a TPU-native neural text-to-speech serving framework.

Capability-parity rebuild of mush42/sonata (see SURVEY.md) designed
TPU-first: the VITS compute path is JAX/XLA (jit/pjit over a device mesh,
Pallas for hot fused ops), the runtime around it is Python + C++ (phonemizer
shim, prosody DSP, C ABI), and the frontends (CLI, gRPC, Python, C) mirror
the reference's surface.
"""

__version__ = "0.1.0"

from .core import (
    AudioInfo,
    BaseModel,
    FailedToLoadResource,
    Model,
    OperationError,
    Phonemes,
    PhonemizationError,
    SonataError,
)
from .audio import Audio, AudioSamples

__all__ = [
    "__version__",
    "AudioInfo",
    "BaseModel",
    "FailedToLoadResource",
    "Model",
    "OperationError",
    "Phonemes",
    "PhonemizationError",
    "SonataError",
    "Audio",
    "AudioSamples",
]
