"""sonata-tpu: a TPU-native neural text-to-speech serving framework.

Capability-parity rebuild of mush42/sonata (see SURVEY.md) designed
TPU-first: the VITS compute path is JAX/XLA (jit/pjit over a device mesh,
Pallas for hot fused ops), the runtime around it is Python + C++ (phonemizer
shim, prosody DSP, C ABI), and the frontends (CLI, gRPC, Python, C) mirror
the reference's surface.
"""

__version__ = "0.1.0"

# Sharding-invariant PRNG semantics, set before any trace can run: with
# the legacy non-partitionable threefry, a random draw INSIDE a sharded
# jit can produce different values than the identical unsharded program
# (observed on jax 0.4.37: duration/decoder noise diverging between a
# meshed and a plain dispatch of the same batch).  Partitionable threefry
# defines draw values independently of how XLA partitions the
# computation, which — together with the per-row keys in
# ``models.vits.per_row_normal`` — is what makes sharded-vs-unsharded
# synthesis bit-stable and a request's audio independent of its batch
# neighbors.  Must happen at import, not first mesh use: flipping the
# flag mid-process would split the executable caches across two RNG
# semantics.
import jax as _jax

_jax.config.update("jax_threefry_partitionable", True)
del _jax

from .core import (
    AudioInfo,
    BaseModel,
    FailedToLoadResource,
    Model,
    OperationError,
    Phonemes,
    PhonemizationError,
    SonataError,
)
from .audio import Audio, AudioSamples

__all__ = [
    "__version__",
    "AudioInfo",
    "BaseModel",
    "FailedToLoadResource",
    "Model",
    "OperationError",
    "Phonemes",
    "PhonemizationError",
    "SonataError",
    "Audio",
    "AudioSamples",
]
