"""Testing utilities: a fake, deterministic, dependency-light model.

The reference has *no* mocks or fake backends anywhere — its model trait is
the natural seam but was never exploited (SURVEY §4: "the ``SonataModel``
trait *is* the natural seam for a fake").  :class:`FakeModel` fills that
gap: a pure-numpy :class:`~sonata_tpu.core.Model` implementation producing
deterministic sine-wave "speech" whose duration scales with phoneme count,
so orchestration layers (synthesizer streams, scheduler, frontends) can be
tested in milliseconds with exact golden metrics — no jax, no compiles.
"""

from __future__ import annotations

import hashlib
import math
from typing import Any, Iterator, Optional

import numpy as np

from .audio import Audio, AudioSamples
from .core import AudioInfo, BaseModel, OperationError, Phonemes
from .models.config import SynthesisConfig
from .text import text_to_phonemes


class FakeModel(BaseModel):
    """Deterministic synthetic voice.

    - each phoneme contributes ``samples_per_phoneme`` samples
      (× ``length_scale``);
    - the waveform is a sine whose frequency is derived from a hash of the
    phoneme string, so different sentences are distinguishable but every
      run is bit-identical;
    - ``inference_ms`` is a fixed constant, making RTF math testable.
    """

    def __init__(self, sample_rate: int = 16000,
                 samples_per_phoneme: int = 160,
                 language: str = "en-us",
                 speakers: Optional[dict[int, str]] = None):
        self._info = AudioInfo(sample_rate=sample_rate)
        self._spp = samples_per_phoneme
        self._language = language
        self._speakers = speakers
        self._config = SynthesisConfig()
        self.calls: list[tuple[str, Any]] = []  # observation log for tests

    # -- Model protocol ------------------------------------------------------
    def audio_output_info(self) -> AudioInfo:
        return self._info

    def get_language(self) -> Optional[str]:
        return self._language

    def get_speakers(self) -> Optional[dict[int, str]]:
        return self._speakers

    def get_default_synthesis_config(self) -> SynthesisConfig:
        return SynthesisConfig()

    def get_fallback_synthesis_config(self) -> SynthesisConfig:
        return self._config.copy()

    def set_fallback_synthesis_config(self, config: Any) -> None:
        if not isinstance(config, SynthesisConfig):
            raise OperationError("invalid synthesis config")
        self._config = config.copy()

    def phonemize_text(self, text: str) -> Phonemes:
        return text_to_phonemes(text, voice=self._language)

    def _synthesize(self, phonemes: str,
                    length_scale: Optional[float] = None) -> Audio:
        ls = (length_scale if length_scale is not None
              else self._config.length_scale)
        n = max(int(len(phonemes) * self._spp * ls), self._spp)
        digest = hashlib.blake2b(phonemes.encode(), digest_size=2).digest()
        freq = 110.0 + (digest[0] % 64) * 10.0
        t = np.arange(n, dtype=np.float32) / self._info.sample_rate
        wave = 0.5 * np.sin(2 * math.pi * freq * t).astype(np.float32)
        return Audio(AudioSamples(wave), self._info, inference_ms=1.0)

    def speak_one_sentence(self, phonemes: str) -> Audio:
        self.calls.append(("speak_one_sentence", phonemes))
        return self._synthesize(phonemes)

    def speak_batch(self, phoneme_batches: list,
                    speakers=None, scales=None) -> list[Audio]:
        # honor the protocol contract: reject what this model cannot
        # represent, and misaligned lists (core.Model.speak_batch docstring)
        for name, lst in (("speakers", speakers), ("scales", scales)):
            if lst is not None and len(lst) != len(phoneme_batches):
                raise OperationError(
                    f"{name} list has {len(lst)} entries for "
                    f"{len(phoneme_batches)} sentences")
        for sid in speakers or []:
            if sid is None:
                continue
            if self._speakers is None:
                if sid != 0:
                    raise OperationError(
                        f"speaker id {sid} on a single-speaker fake")
            elif sid not in self._speakers:
                raise OperationError(f"unknown speaker id {sid}")
        self.calls.append(("speak_batch", list(phoneme_batches), speakers,
                           scales))
        # dispatch attribution parity with PiperVoice: the fake pads
        # nothing and never compiles, and says so on the channel (no-op
        # outside a scheduler dispatch), so span-tree tests and the CI
        # smoke can assert the attribution contract without jax
        from .serving import tracing

        tracing.annotate_dispatch_group(
            batch_bucket=len(phoneme_batches),
            text_bucket=max((len(p) for p in phoneme_batches), default=0),
            rows=len(phoneme_batches), padding_rows=0, padding_ratio=0.0,
            compile="none")
        out = []
        for i, p in enumerate(phoneme_batches):
            sc = scales[i] if scales and i < len(scales) and scales[i] else None
            out.append(self._synthesize(p, length_scale=(
                sc.length_scale if sc else None)))
        return out

    # -- bucket-lattice warmup contract (serving/warmup.py) ------------------
    #: per-shape synthetic "compile" cost; tests raise it to exercise
    #: the SONATA_WARMUP_BUDGET_S expiry path deterministically
    warm_delay_s: float = 0.0
    #: the lattice a fake replica advertises — small and fixed so tests
    #: can assert exact coverage (full ⊃ minimal, like the real voice)
    _LATTICE_FULL = ((1, 16, 64), (1, 16, 128), (1, 32, 128),
                     (2, 16, 64), (2, 32, 128))
    _LATTICE_MINIMAL = ((1, 16, 64), (1, 32, 128))

    def lattice_shapes(self, mode: str = "full") -> list:
        if mode == "off":
            return []
        return list(self._LATTICE_MINIMAL if mode == "minimal"
                    else self._LATTICE_FULL)

    def warm_shape(self, shape) -> None:
        self.calls.append(("warm_shape", tuple(shape)))
        if self.warm_delay_s:
            import time

            time.sleep(self.warm_delay_s)

    @property
    def warmed_shapes(self) -> list:
        return [c[1] for c in self.calls if c[0] == "warm_shape"]

    def supports_streaming_output(self) -> bool:
        return True

    def stream_synthesis(self, phonemes: str, chunk_size: int,
                         chunk_padding: int,
                         deadline=None) -> Iterator[Audio]:
        self.calls.append(("stream_synthesis", phonemes, chunk_size,
                           chunk_padding))
        audio = self._synthesize(phonemes)
        data = audio.samples.data
        step = max(chunk_size * 16, 1)
        for start in range(0, len(data), step):
            yield Audio(AudioSamples(data[start:start + step]), self._info,
                        inference_ms=0.5)
